"""Async serving subsystem (lightgbm_tpu/serving/).

Contracts under test:

* continuous batching — coalesced and chunked requests reproduce the
  sync path's RAW scores bit-for-bit (raw device scores are bit-exact
  across batch shapes; the transformed sigmoid may differ by 1 ulp, so
  bit-exact assertions here always use ``raw_score=True``);
* deadline-aware flush — a lone sub-bucket request is flushed within
  ``max_wait`` (pinned via the arrival-time queue-wait histogram), not
  starved waiting for a full bucket;
* atomic hot-swap — under concurrent load, every answered request is
  EXACTLY one model's output (never a mix), nothing is dropped, and
  rollback restores bit-exact pre-swap scores (same predictor object);
* quantized admission — f16 is certified against PREDICT_REL_BUDGET and
  admitted; int8's certificate fails and the load is REFUSED with the
  certificate named, leaving the old model serving.

Feature values live on a coarse grid (k/4 for small integer k) so the
f16 threshold snap cannot flip any decision — tree routing is identical
between the native and quantized ensembles and only leaf precision
differs.

The three trained models are module-scoped (tier-1 wall-time budget):
tests only predict through them and load them into registries — nothing
mutates a shared Booster.
"""
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serving import (AsyncBatchServer, ModelRegistry,
                                  QuantRefusedError, ServingError)
from lightgbm_tpu.telemetry import events


@pytest.fixture
def counters():
    prev_mode = events.mode()
    events.enable("timers")
    events.reset()
    yield events.counts_snapshot
    events.reset()
    if prev_mode == events.OFF:
        events.disable()


def _grid_data(seed=3, n=1500, nf=8):
    """Coarse-grid features (k/4): f16 threshold snaps cannot reorder
    any feature value around a split, so quantized trees route rows
    identically and only leaf values carry quantization error."""
    rng = np.random.default_rng(seed)
    X = (rng.integers(0, 16, size=(n, nf)) / 4.0).astype(np.float64)
    y = (X[:, 0] - X[:, 2] + 0.25 * X[:, 5] > 0.5).astype(float)
    return X, y


def _train(X, y, n_trees=12, seed=0, leaves=15):
    params = {"objective": "binary", "num_leaves": leaves,
              "verbosity": -1, "min_data_in_leaf": 5,
              "feature_fraction": 0.9, "seed": seed,
              "deterministic": True}
    return lgb.train(dict(params), lgb.Dataset(X, y, params=params),
                     n_trees, verbose_eval=False)


@pytest.fixture(scope="module")
def data():
    return _grid_data()


@pytest.fixture(scope="module")
def model(data):
    """Default 12-tree model + its raw reference scores."""
    X, y = data
    b = _train(X, y)
    return b, b.predict(X, raw_score=True)


@pytest.fixture(scope="module")
def model_pair(data):
    """Two distinguishable models for swap tests (+ raw references)."""
    X, y = data
    ba = _train(X, y, seed=1)
    bb = _train(X, y, n_trees=20, seed=9)
    ref_a = ba.predict(X, raw_score=True)
    ref_b = bb.predict(X, raw_score=True)
    # distinguishable (else "no mixed outputs" is vacuous)
    assert not np.array_equal(ref_a, ref_b)
    return (ba, ref_a), (bb, ref_b)


# ---------------------------------------------------------------------
# continuous batching


def test_async_parity_single_request(data, model):
    X, _ = data
    b, ref_raw = model
    with AsyncBatchServer(b._booster.device_predictor(),
                          min_batch=256, max_batch=1024) as server:
        np.testing.assert_array_equal(
            server.predict(X[:300], raw_score=True), ref_raw[:300])
        # transformed output: float-ulp level (batch-shape dependent)
        np.testing.assert_allclose(server.predict(X[:300]),
                                   b.predict(X[:300]),
                                   rtol=0, atol=1e-12)


def test_coalesces_queued_requests_into_one_batch(counters, data, model):
    X, _ = data
    b, ref_raw = model
    server = AsyncBatchServer(b._booster.device_predictor(),
                              min_batch=256, max_batch=1024)
    # deterministic coalescing: all 8 requests are queued BEFORE the
    # loop starts, so the first admit wave takes the whole prefix
    futs = [(i, server.submit(X[i * 40:(i + 1) * 40], raw_score=True))
            for i in range(8)]
    server.start()
    try:
        for i, f in futs:
            np.testing.assert_array_equal(
                f.result(timeout=30), ref_raw[i * 40:(i + 1) * 40])
    finally:
        server.stop()
    st = server.stats()
    assert st["batches"] == 1, st
    assert st["requests"] == 8
    assert st["coalesce_ratio"] == 8.0
    assert st["errors"] == 0
    counts = counters()
    assert counts.get("serving::batches", 0) == 1
    assert counts.get("serving::coalesced_requests", 0) == 8


def test_oversized_request_chunked_multi_part(data, model):
    X, _ = data
    b, ref_raw = model
    with AsyncBatchServer(b._booster.device_predictor(),
                          min_batch=64, max_batch=256) as server:
        out = server.predict(X, raw_score=True)   # 1500 rows -> 6 parts
    np.testing.assert_array_equal(out, ref_raw)


def test_deadline_flush_lone_subbucket_request(data, model):
    """A lone 32-row request (min bucket 256) must NOT starve: the
    deadline branch flushes it within max_wait. Pinned on the
    arrival-time queue-wait histogram: the wait shows the hold (the
    request really was held for coalescing) but stays within the
    budget plus scheduling slack."""
    X, _ = data
    b, ref_raw = model
    max_wait_ms = 50.0
    with AsyncBatchServer(b._booster.device_predictor(), min_batch=256,
                          max_batch=1024,
                          max_wait_ms=max_wait_ms) as server:
        t0 = time.perf_counter()
        out = server.predict(X[:32], raw_score=True)
        e2e = time.perf_counter() - t0
    np.testing.assert_array_equal(out, ref_raw[:32])
    st = server.stats()
    assert st["flushes"]["deadline"] >= 1, st["flushes"]
    # held for (most of) the coalescing window...
    assert st["queue_wait_max"] >= 0.5 * max_wait_ms / 1e3, st
    # ...but flushed within the budget (+ generous scheduler slack)
    assert st["queue_wait_max"] <= max_wait_ms / 1e3 + 0.3, st
    assert e2e < 5.0


def test_stop_drains_queue(data, model):
    X, _ = data
    b, _ = model
    server = AsyncBatchServer(b._booster.device_predictor(),
                              min_batch=256, max_batch=1024)
    futs = [server.submit(X[i * 30:(i + 1) * 30]) for i in range(6)]
    server.start()
    server.stop()         # drain=True: every queued request answered
    assert all(f.done() for f in futs)
    ref = b.predict(X[:180])
    for i, f in enumerate(futs):
        np.testing.assert_allclose(f.result(), ref[i * 30:(i + 1) * 30],
                                   rtol=0, atol=1e-12)
    with pytest.raises(ServingError):
        server.submit(X[:8])


# ---------------------------------------------------------------------
# hot-swap registry


def test_registry_swap_rollback_bit_exact(counters, data, model_pair):
    X, _ = data
    (ba, ref_a), (bb, ref_b) = model_pair
    reg = ModelRegistry()
    reg.load("a", booster=ba)          # first load auto-activates
    reg.load("b", booster=bb)          # loaded, NOT active
    assert reg.active().name == "a"
    pred_a = reg.resolve()
    with AsyncBatchServer(reg, min_batch=64, max_batch=512) as server:
        np.testing.assert_array_equal(
            server.predict(X[:100], raw_score=True), ref_a[:100])
        reg.swap("b")
        np.testing.assert_array_equal(
            server.predict(X[:100], raw_score=True), ref_b[:100])
        reg.rollback()
        # bit-exact: the rollback restores the SAME predictor object
        assert reg.resolve() is pred_a
        np.testing.assert_array_equal(
            server.predict(X[:100], raw_score=True), ref_a[:100])
    st = reg.stats()
    assert st["active"] == "a" and st["previous"] == "b"
    assert st["swaps"] == 3            # load-a activate, swap-b, rollback
    counts = counters()
    assert counts.get("serving::swap", 0) >= 3
    assert counts.get("serving::rollback", 0) == 1
    assert counts.get("serving::model_load", 0) == 2


def test_hot_swap_under_load_no_mixed_outputs_no_drops(data, model_pair):
    """Concurrent clients + repeated swaps: every answered request must
    equal EXACTLY one model's raw output over its rows — a request that
    mixed two models' trees would match neither — and every submitted
    request is answered (zero drops)."""
    X, _ = data
    (ba, ref_a), (bb, ref_b) = model_pair
    reg = ModelRegistry()
    reg.load("a", booster=ba)
    reg.load("b", booster=bb)
    n_clients, per_client = 6, 15
    results = [[] for _ in range(n_clients)]
    errors = []
    stop_swapping = threading.Event()

    def client(ci, server, rng):
        for _ in range(per_client):
            k = int(rng.integers(5, 120))
            i0 = int(rng.integers(0, len(X) - k))
            try:
                out = server.predict(X[i0:i0 + k], raw_score=True)
                results[ci].append((i0, k, out))
            except Exception as exc:   # noqa: BLE001 — recorded, failed
                errors.append(exc)     # below with full context

    def swapper(reg):
        flip = True
        while not stop_swapping.is_set():
            reg.swap("b" if flip else "a")
            flip = not flip
            time.sleep(0.002)

    with AsyncBatchServer(reg, min_batch=64, max_batch=1024,
                          max_wait_ms=2.0) as server:
        threads = [threading.Thread(
            target=client,
            args=(ci, server, np.random.default_rng(100 + ci)))
            for ci in range(n_clients)]
        sw = threading.Thread(target=swapper, args=(reg,))
        for t in threads:
            t.start()
        sw.start()
        for t in threads:
            t.join()
        stop_swapping.set()
        sw.join()
        st = server.stats()
    assert errors == [], errors
    # zero drops: every submitted request produced an answer
    assert sum(len(r) for r in results) == n_clients * per_client
    assert st["requests"] == n_clients * per_client
    assert st["errors"] == 0 and st["depth"] == 0
    for ci in range(n_clients):
        for i0, k, out in results[ci]:
            from_a = np.array_equal(out, ref_a[i0:i0 + k])
            from_b = np.array_equal(out, ref_b[i0:i0 + k])
            assert from_a or from_b, (
                "request rows [%d:%d] matches NEITHER model bit-exactly "
                "— a mixed-model batch" % (i0, i0 + k))


def test_registry_load_sources_and_drop(tmp_path, data, model):
    X, _ = data
    b, ref = model
    txt = b._booster.save_model_to_string()
    reg = ModelRegistry()
    reg.load("from_str", model_str=txt)
    mf = tmp_path / "m.txt"
    mf.write_text(txt)
    reg.load("from_file", model_file=str(mf))
    # checkpoint source: the resilience kind=model snapshot format
    from lightgbm_tpu.resilience.checkpoint import CheckpointWriter
    w = CheckpointWriter(str(tmp_path / "ckpt"), keep=2, cfg_hash="x")
    path = w.write_model_text(txt, iteration=7)
    reg.load("from_ckpt", checkpoint=path)
    assert reg.names() == ["from_ckpt", "from_file", "from_str"]
    for name in reg.names():
        pred = reg.resolve(name)
        with AsyncBatchServer(pred, min_batch=64,
                              max_batch=512) as server:
            np.testing.assert_array_equal(
                server.predict(X[:64], raw_score=True), ref[:64])
    with pytest.raises(ValueError):
        reg.load("two", booster=b, model_str=txt)
    with pytest.raises(RuntimeError):
        reg.drop(reg.active().name)
    reg.swap("from_file")
    reg.drop("from_str")
    assert "from_str" not in reg.names()


# ---------------------------------------------------------------------
# quantized ensembles


def test_f16_quantized_admitted_and_within_budget(counters, data,
                                                  model_pair):
    from lightgbm_tpu.analysis.quant_audit import PREDICT_REL_BUDGET
    X, _ = data
    (bb, ref) = model_pair[1]          # the deeper 20-tree model
    reg = ModelRegistry()
    slot = reg.load("q", booster=bb, quant="f16")
    assert slot.certificate is not None
    assert slot.certificate["ok"]
    assert slot.certificate["bound"] <= PREDICT_REL_BUDGET
    with AsyncBatchServer(reg, min_batch=256, max_batch=1024) as server:
        out = server.predict(X, raw_score=True)
    # coarse-grid features: routing identical, leaf precision is the
    # only error source. The certificate bounds each stored VALUE's
    # relative error; end-to-end that bounds the summed score relative
    # to the score SCALE (element-wise ratios diverge where opposing
    # trees cancel to a near-zero raw score — not what is certified)
    rel = float(np.max(np.abs(out - ref)) / np.max(np.abs(ref)))
    assert rel <= PREDICT_REL_BUDGET, rel
    assert counters().get("serving::quant_admitted", 0) == 1


def test_int8_refused_names_certificate_old_model_serves(counters, data,
                                                         model_pair):
    X, _ = data
    ba, ref_a = model_pair[0]
    reg = ModelRegistry()
    reg.load("a", booster=ba)
    with AsyncBatchServer(reg, min_batch=64, max_batch=512) as server:
        with pytest.raises(QuantRefusedError,
                           match="leaf_int8") as ei:
            reg.load("crushed", booster=ba, quant="int8",
                     activate=True)
        assert ei.value.certificate["ok"] is False
        # the refused load left the registry untouched: old model
        # active and still serving bit-exact
        assert reg.active().name == "a"
        assert "crushed" not in reg.names()
        np.testing.assert_array_equal(
            server.predict(X[:80], raw_score=True), ref_a[:80])
    assert counters().get("serving::quant_refused", 0) == 1
    with pytest.raises(QuantRefusedError,
                       match="unknown quantization target"):
        reg.load("x", booster=ba, quant="int4")


# ---------------------------------------------------------------------
# satellites: sync-server qdepth, lint scope, audit domains


def test_batchserver_qdepth_sampled_at_admission(data, model):
    from lightgbm_tpu.predict import BatchServer
    X, _ = data
    b, _ = model
    server = BatchServer(b._booster.device_predictor(), min_batch=64,
                         max_batch=512)
    barrier = threading.Barrier(3)

    def one():
        barrier.wait()
        server.predict(X[:64])

    threads = [threading.Thread(target=one) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    st = server.stats()
    # admission-time sampling: 3 concurrent requests were all admitted
    # before any finished, so the max depth must see the pile-up (the
    # old post-serve sampling always read back ~1)
    assert st["qdepth_max"] >= 2, st["qdepth_max"]
    assert st["queue_depth"]["count"] == 3
    server.predict(X[:64])
    assert server.stats()["qdepth_max"] >= 2   # max is sticky


def test_jg002_scope_covers_serving():
    from lightgbm_tpu.analysis.config import GraftlintConfig
    cfg = GraftlintConfig()
    assert any("serving" in p for p in cfg.hot_paths), cfg.hot_paths
    # and the serving loop passes its own lint: no lexical host sync
    # in the service loop (the deliberate per-batch sync lives in
    # helper methods)
    import io
    import os
    from lightgbm_tpu.analysis.lint import lint_source
    src_path = os.path.join(os.path.dirname(lgb.__file__),
                            "serving", "server.py")
    with io.open(src_path, "r", encoding="utf-8") as f:
        findings = lint_source(f.read(),
                               relpath="lightgbm_tpu/serving/server.py",
                               config=cfg)
    assert [f for f in findings if f.rule == "JG002"] == []


def test_compile_audit_serving_domains():
    from lightgbm_tpu.analysis import compile_audit
    assert "lightgbm_tpu/serving" in compile_audit.AUDIT_ROOTS
    assert "quant_target" in compile_audit.DOMAINS
    assert "raw_score" in compile_audit.DOMAINS
    from lightgbm_tpu.analysis.config import GraftlintConfig
    surf = compile_audit.compile_surface()
    assert surf["serving_ladder_per_slot"] >= 1
    assert surf["serving_ladder_per_slot"] == surf["serve_ladder_bound"]
    ceiling = int(getattr(GraftlintConfig(), "compile_ceiling", 64))
    assert surf["total_bound"] <= ceiling


def test_prom_export_serving_families_explicit_zero():
    from lightgbm_tpu.telemetry import promexport
    text = promexport.render()
    assert 'lgbtpu_serving_total{kind="requests"}' in text
    assert 'lgbtpu_serving_model_total{kind="quant_refused"}' in text
