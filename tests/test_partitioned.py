"""Partitioned (payload-sorting) grower vs masked grower equivalence.

The two growers must produce identical trees on numerical data (identical
histograms up to f32 summation order; with a fixed seed the argmaxes are
stable). With categorical features a near-tie in the sorted categorical scan
can legitimately pick an equal-gain split from the other scan direction, so
that case asserts prediction-level closeness instead of bit equality.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.data.dataset import BinnedDataset
from lightgbm_tpu.ops.grow import grow_tree, grow_tree_partitioned
from lightgbm_tpu.treelearner.serial import SerialTreeLearner


def _make(n, seed=3, cats=False):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 8))
    X[rng.random((n, 8)) < 0.08] = np.nan          # NaN missing
    X[:, 5] = np.where(rng.random(n) < 0.8, 0.0, X[:, 5])  # sparse zeros
    if cats:
        X[:, 2] = rng.integers(0, 12, size=n)
    y = np.nan_to_num(X[:, 0]) + 0.3 * np.nan_to_num(X[:, 1]) \
        + rng.normal(size=n) * 0.1
    return X, y


def _grow_both(X, y, leaves, wc, cat_cols=()):
    n = len(y)
    cfg = lgb.Config({"num_leaves": leaves, "max_bin": 63,
                      "min_data_in_leaf": 5, "tpu_window_chunk": wc})
    ds = BinnedDataset.from_matrix(X, cfg, categorical_features=cat_cols,
                                   label=y)
    le = SerialTreeLearner(cfg, ds)
    g = jnp.asarray((y - y.mean()).astype(np.float32))
    h = jnp.ones(n, jnp.float32)
    args = (le.layout, g, h, jnp.ones(n, bool), le.meta, le.params,
            jnp.ones(ds.num_features, bool), le.fix, le.grow_config)
    a1, _ = grow_tree(*args, cat=le.cat_layout)
    a2, _ = grow_tree_partitioned(*args, gw_global=le.gw_global,
                                  cat=le.cat_layout)
    return ds, le, a1, a2


@pytest.mark.parametrize("wc,leaves", [
    (256, 31),
    pytest.param(1024, 63,
                 marks=pytest.mark.slow),  # tier-1 870s budget:
    (256, 4)])                             # smaller variants stay
def test_partitioned_matches_masked_numerical(wc, leaves):
    X, y = _make(4000)
    _, _, a1, a2 = _grow_both(X, y, leaves, wc)
    for fld in a1._fields:
        if fld == "default_left":
            # when a leaf holds no missing rows the forward/reverse scans tie
            # exactly and ulp-level histogram differences pick either winner;
            # routing is identical either way (leaf_count/row_leaf prove it)
            continue
        v1, v2 = np.asarray(getattr(a1, fld)), np.asarray(getattr(a2, fld))
        if v1.dtype.kind == "f":
            np.testing.assert_allclose(v1, v2, rtol=1e-4, atol=1e-6,
                                       err_msg=fld)
        else:
            np.testing.assert_array_equal(v1, v2, err_msg=fld)


def test_partitioned_row_leaf_is_consistent_partition():
    """row_leaf must agree with the recorded split decisions row by row."""
    X, y = _make(3000, seed=11)
    ds, le, _, a2 = _grow_both(X, y, 31, 512)
    rl = np.asarray(a2.row_leaf)
    counts = np.bincount(rl, minlength=31)
    np.testing.assert_array_equal(
        counts[:int(a2.num_leaves)],
        np.asarray(a2.leaf_count)[:int(a2.num_leaves)])


@pytest.mark.slow  # tier-1 870s budget: cheaper sibling tests cover this area
def test_partitioned_categorical_close():
    X, y = _make(4000, cats=True)
    _, _, a1, a2 = _grow_both(X, y, 63, 1024, cat_cols=[2])
    # same number of leaves and near-identical gains even if a near-tie picks
    # a different equal-gain categorical mask
    assert int(a1.num_leaves) == int(a2.num_leaves)
    np.testing.assert_allclose(np.asarray(a1.gain), np.asarray(a2.gain),
                               rtol=1e-3, atol=1e-5)
    np.testing.assert_allclose(np.asarray(a1.leaf_value).sum(),
                               np.asarray(a2.leaf_value).sum(),
                               rtol=1e-2, atol=1e-4)


def test_train_partitioned_end_to_end(monkeypatch):
    """Full train loop through the partitioned path (patched threshold)."""
    import lightgbm_tpu.treelearner.serial as serial_mod
    monkeypatch.setattr(serial_mod, "PARTITION_MIN_ROWS", 100)
    X, y = _make(3000, seed=7)
    labels = (y > np.median(y)).astype(float)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1}, lgb.Dataset(X, labels), 10,
                    verbose_eval=False)
    p = bst.predict(X)
    acc = ((p > 0.5) == labels).mean()
    assert acc > 0.85, acc


@pytest.mark.slow  # tier-1 870s budget: cheaper sibling tests cover this area
def test_batched_scan_matches_single_iterations(monkeypatch):
    """The fused K-iteration scan must produce the exact model the
    single-iteration path produces — same trees, same predictions (the
    per-tree RNG streams and histogram accumulation order are identical)."""
    import lightgbm_tpu.treelearner.serial as serial_mod
    monkeypatch.setattr(serial_mod, "PARTITION_MIN_ROWS", 100)
    X, y = _make(3000, seed=11)
    labels = (y > np.median(y)).astype(float)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    # batched: engine enables the fused scan (no callbacks, 20 >= 16)
    b_batch = lgb.train(dict(params), lgb.Dataset(X, labels), 20,
                        verbose_eval=False)
    # per-iteration: a BEFORE-iteration callback disables batching
    seen = []

    def cb(env):
        seen.append(env.iteration)
    cb.before_iteration = True
    cb.order = 0
    b_single = lgb.train(dict(params), lgb.Dataset(X, labels), 20,
                         callbacks=[cb], verbose_eval=False)
    assert len(seen) == 20
    assert not b_single._booster._pending_batches
    t_b = b_batch.model_to_string().split("parameters:")[0]
    t_s = b_single.model_to_string().split("parameters:")[0]
    assert t_b == t_s
    np.testing.assert_array_equal(b_batch.predict(X), b_single.predict(X))
