"""Cross-check the vectorized split scan against a literal (loopy) numpy
re-implementation of the reference algorithm
(FeatureHistogram::FindBestThresholdSequentially,
/root/reference/src/treelearner/feature_histogram.hpp:770-948).

The numpy oracle below is written directly from the reference's control flow
(sequential loops, breaks, continues) as an independent implementation, so a
mismatch indicates a real semantics bug in the vectorized kernel.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.data.dataset import BinnedDataset
from lightgbm_tpu.ops.grow import GrowConfig, grow_tree
from lightgbm_tpu.ops.split import SplitParams, find_best_split_numerical

import jax.numpy as jnp

K_EPS = 1e-15


def leaf_gain(g, h, l1, l2):
    sg = np.sign(g) * max(0.0, abs(g) - l1)
    return sg * sg / (h + l2)


def oracle_scan(hist, sum_grad, sum_hess, num_data, num_bin, missing_type,
                default_bin, l1, l2, min_data, min_hess, min_gain):
    """Literal transcription of the reference scan dispatch + both directions."""
    sum_hess = sum_hess + 2 * K_EPS
    cnt_factor = num_data / sum_hess
    gain_shift = leaf_gain(sum_grad, sum_hess, l1, l2)
    min_gain_shift = gain_shift + min_gain

    best = dict(gain=-np.inf, threshold=None, default_left=True)

    def scan(reverse, skip_default, na_as_missing):
        nonlocal best
        local_best_gain = -np.inf
        local_best_t = None
        if reverse:
            sum_right_g, sum_right_h, right_cnt = 0.0, K_EPS, 0
            t = num_bin - 1 - int(na_as_missing)
            while t >= 1:
                if skip_default and t == default_bin:
                    t -= 1
                    continue
                g, h = hist[t]
                cnt = int(np.floor(h * cnt_factor + 0.5))
                sum_right_g += g
                sum_right_h += h
                right_cnt += cnt
                thr = t - 1
                t -= 1
                if right_cnt < min_data or sum_right_h < min_hess:
                    continue
                left_cnt = num_data - right_cnt
                if left_cnt < min_data:
                    break
                sum_left_h = sum_hess - sum_right_h
                if sum_left_h < min_hess:
                    break
                sum_left_g = sum_grad - sum_right_g
                cur = leaf_gain(sum_left_g, sum_left_h, l1, l2) + \
                    leaf_gain(sum_right_g, sum_right_h, l1, l2)
                if cur <= min_gain_shift:
                    continue
                if cur > local_best_gain:
                    local_best_gain = cur
                    local_best_t = thr
            if local_best_t is not None and local_best_gain > best["gain"]:
                best = dict(gain=local_best_gain, threshold=local_best_t,
                            default_left=True)
        else:
            sum_left_g, sum_left_h, left_cnt = 0.0, K_EPS, 0
            for t in range(0, num_bin - 1):
                if skip_default and t == default_bin:
                    continue
                g, h = hist[t]
                cnt = int(np.floor(h * cnt_factor + 0.5))
                sum_left_g += g
                sum_left_h += h
                left_cnt += cnt
                if left_cnt < min_data or sum_left_h < min_hess:
                    continue
                right_cnt = num_data - left_cnt
                if right_cnt < min_data:
                    break
                sum_right_h = sum_hess - sum_left_h
                if sum_right_h < min_hess:
                    break
                sum_right_g = sum_grad - sum_left_g
                cur = leaf_gain(sum_left_g, sum_left_h, l1, l2) + \
                    leaf_gain(sum_right_g, sum_right_h, l1, l2)
                if cur <= min_gain_shift:
                    continue
                if cur > local_best_gain:
                    local_best_gain = cur
                    local_best_t = t
            if local_best_t is not None and local_best_gain > best["gain"]:
                best = dict(gain=local_best_gain, threshold=local_best_t,
                            default_left=False)

    if num_bin > 2 and missing_type != 0:
        if missing_type == 1:  # Zero
            scan(True, True, False)
            scan(False, True, False)
        else:                  # NaN
            scan(True, False, True)
            scan(False, False, True)
    else:
        scan(True, False, False)
        if missing_type == 2:
            best["default_left"] = False
    if best["threshold"] is None:
        return None
    best["gain"] -= min_gain_shift
    return best


def _setup(X, y, params):
    cfg = lgb.Config(params)
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    layout, meta = ds.to_device(cfg)
    p = 0.5
    grad = jnp.asarray((p - y).astype(np.float32))
    hess = jnp.asarray(np.full(len(y), p * (1 - p), np.float32))
    return cfg, ds, layout, meta, grad, hess


@pytest.mark.parametrize("missing_mode", ["none", "nan", "zero_sparse"])
def test_root_split_matches_oracle(missing_mode):
    rng = np.random.default_rng(42)
    n, f = 1500, 5
    X = rng.normal(size=(n, f))
    if missing_mode == "nan":
        X[rng.random((n, f)) < 0.15] = np.nan
    elif missing_mode == "zero_sparse":
        X[rng.random((n, f)) < 0.6] = 0.0
    y = (np.nan_to_num(X[:, 0]) + 0.3 * np.nan_to_num(X[:, 2]) > 0.2).astype(np.float64)

    params = {"max_bin": 31, "min_data_in_leaf": 25, "num_leaves": 4,
              "min_sum_hessian_in_leaf": 1e-3, "enable_bundle": False}
    cfg, ds, layout, meta, grad, hess = _setup(X, y, params)

    # device scan
    from lightgbm_tpu.ops.split import FeatureMeta  # noqa
    hist_np = np.zeros((ds.total_bins, 2), np.float64)
    gnp = np.asarray(grad, np.float64)
    hnp = np.asarray(hess, np.float64)
    binned = np.asarray(layout.bins, np.int64) + np.asarray(layout.group_offset)[None, :]
    for j in range(binned.shape[1]):
        np.add.at(hist_np[:, 0], binned[:, j], gnp)
        np.add.at(hist_np[:, 1], binned[:, j], hnp)

    cand = find_best_split_numerical(
        jnp.asarray(hist_np, jnp.float32),
        jnp.asarray(gnp.sum()), jnp.asarray(hnp.sum()),
        jnp.asarray(n, jnp.int32), meta, SplitParams.from_config(cfg),
        jnp.asarray(-np.inf), jnp.asarray(np.inf),
        jnp.ones(ds.num_features, bool),
        num_features=ds.num_features, use_mc=False)

    # oracle over every feature
    hist32 = np.asarray(jnp.asarray(hist_np, jnp.float32), np.float64)
    best_f, best = -1, None
    for i in range(ds.num_features):
        s, e = ds.bin_start[i], ds.bin_end[i]
        r = oracle_scan(hist32[s:e], gnp.sum(), hnp.sum(), n, e - s,
                        int(ds.missing_type_arr[i]), int(ds.default_bin[i]),
                        0.0, 0.0, 25, 1e-3, 0.0)
        if r is not None and (best is None or r["gain"] > best["gain"]):
            best, best_f = r, i

    assert best is not None
    assert int(cand.feature) == best_f
    assert int(cand.threshold) == best["threshold"]
    assert bool(cand.default_left) == best["default_left"]
    np.testing.assert_allclose(float(cand.gain), best["gain"], rtol=1e-6)


def test_grow_tree_respects_min_data():
    rng = np.random.default_rng(7)
    n, f = 3000, 8
    X = rng.normal(size=(n, f))
    y = (X[:, 0] * X[:, 1] > 0).astype(np.float64)
    params = {"max_bin": 63, "min_data_in_leaf": 40, "num_leaves": 31}
    cfg, ds, layout, meta, grad, hess = _setup(X, y, params)
    gc = GrowConfig(num_leaves=31, total_bins=ds.total_bins,
                    num_features=ds.num_features, use_mc=False, max_depth=-1,
                    rows_per_chunk=0, cat_width=1)
    tree, _ = grow_tree(layout, grad, hess, jnp.ones(n, bool), meta,
                     SplitParams.from_config(cfg),
                     jnp.ones(ds.num_features, bool), ds.fix_info(), gc)
    nl = int(tree.num_leaves)
    counts = np.asarray(tree.leaf_count[:nl])
    assert counts.sum() == n
    assert counts.min() >= 40
    assert (np.asarray(tree.gain[:nl - 1]) > 0).all()


def test_max_depth_limits_tree():
    rng = np.random.default_rng(3)
    n, f = 2000, 4
    X = rng.normal(size=(n, f))
    y = X[:, 0] + np.sin(X[:, 1] * 3)
    params = {"max_bin": 63, "min_data_in_leaf": 5, "num_leaves": 64,
              "max_depth": 3}
    cfg, ds, layout, meta, grad, hess = _setup(X, y, params)
    grad = jnp.asarray((np.asarray(grad) * 0 - y).astype(np.float32))
    gc = GrowConfig(num_leaves=64, total_bins=ds.total_bins,
                    num_features=ds.num_features, use_mc=False, max_depth=3,
                    rows_per_chunk=0, cat_width=1)
    tree, _ = grow_tree(layout, grad, hess, jnp.ones(n, bool), meta,
                     SplitParams.from_config(cfg),
                     jnp.ones(ds.num_features, bool), ds.fix_info(), gc)
    assert int(tree.num_leaves) <= 8  # depth 3 -> at most 2^3 leaves
