"""The production multi-chip configuration: grow_tree_partitioned UNDER
shard_map (the path a real v5e-8 runs for large sharded data) must produce
the same trees as the serial grower — for the data-parallel AND
voting-parallel modes (reference contract:
src/treelearner/data_parallel_tree_learner.cpp:163-250,
voting_parallel_tree_learner.cpp:153-344).

PARTITION_MIN_ROWS is monkeypatched down so the partitioned grower engages
at CI-sized data; psum-in-pass-A and the per-shard payload sorting are the
code under test.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.data.dataset import BinnedDataset


def _data(n=6000, f=8, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] - 0.8 * X[:, 2] + 0.3 * X[:, 5]
         + rng.normal(size=n) * 0.3 > 0).astype(float)
    return X, y


def _grow(learner_cls_name, cfg, ds, grad, hess, monkeypatch, force_part):
    from lightgbm_tpu.parallel import learners as L
    from lightgbm_tpu.treelearner import serial as S
    if force_part:
        monkeypatch.setattr(S, "PARTITION_MIN_ROWS", 128)
        monkeypatch.setattr(L, "PARTITION_MIN_ROWS", 128)
    if learner_cls_name == "serial":
        learner = S.SerialTreeLearner(cfg, ds)
        learner.use_partitioned = force_part or learner.use_partitioned
    else:
        learner = getattr(L, learner_cls_name)(cfg, ds)
    n = ds.num_data
    bag = jnp.ones(n, bool)
    tree, _ = learner.train(jnp.asarray(grad, jnp.float32),
                            jnp.asarray(hess, jnp.float32), bag)
    return tree


@pytest.mark.parametrize("mode", ["DataParallelTreeLearner",
                                  "VotingParallelTreeLearner"])
@pytest.mark.slow  # tier-1 870s budget: cheaper sibling tests cover this area
def test_sharded_partitioned_matches_serial(mode, monkeypatch):
    X, y = _data()
    params = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
              "min_data_in_leaf": 5, "top_k": 8}
    cfg = Config(dict(params))
    ds = lgb.Dataset(X, y)
    ds.construct()
    inner = ds._inner
    rng = np.random.default_rng(0)
    grad = rng.normal(size=len(y)).astype(np.float32)
    hess = (rng.random(len(y)).astype(np.float32) * 0.2 + 0.05)

    t_serial = _grow("serial", cfg, inner, grad, hess, monkeypatch,
                     force_part=True)
    t_shard = _grow(mode, cfg, inner, grad, hess, monkeypatch,
                    force_part=True)
    k = t_serial.num_leaves
    assert t_shard.num_leaves == k
    np.testing.assert_array_equal(
        t_shard.split_feature[:k - 1], t_serial.split_feature[:k - 1])
    np.testing.assert_array_equal(
        t_shard.threshold_in_bin[:k - 1], t_serial.threshold_in_bin[:k - 1])
    np.testing.assert_allclose(
        t_shard.leaf_value[:k], t_serial.leaf_value[:k], rtol=2e-5, atol=1e-8)


def test_sharded_partitioned_actually_partitions(monkeypatch):
    """Guard: with the threshold patched the sharded learner must really
    choose the partitioned grower (the configuration under test)."""
    from lightgbm_tpu.parallel import learners as L
    from lightgbm_tpu.treelearner import serial as S
    monkeypatch.setattr(S, "PARTITION_MIN_ROWS", 128)
    monkeypatch.setattr(L, "PARTITION_MIN_ROWS", 128)
    X, y = _data()
    cfg = Config({"objective": "binary", "num_leaves": 15, "verbosity": -1})
    ds = lgb.Dataset(X, y)
    ds.construct()
    learner = L.DataParallelTreeLearner(cfg, ds._inner)
    n_shard = (ds._inner.num_data + learner._pad) // learner.num_shards
    assert n_shard >= 128
    # the _build closure picks partitioned iff n_shard >= threshold
    assert n_shard >= L.PARTITION_MIN_ROWS
