"""CLI vs Python-API consistency on the reference's example configs — the
analog of tests/python_package_test/test_consistency.py:69-118: training
through `python -m lightgbm_tpu config=train.conf` must produce the exact
model the Python API produces from the same parameters, and its
predictions must round-trip through task=predict."""
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config

EXAMPLES = "/root/reference/examples"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(EXAMPLES),
    reason="reference examples not available")

DET = ["feature_fraction=1.0", "bagging_fraction=1.0", "bagging_freq=0",
       "enable_bundle=false", "num_trees=15", "verbosity=-1"]


def _run_cli(args, cwd):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH="/root/repo")
    r = subprocess.run([sys.executable, "-m", "lightgbm_tpu"] + args,
                       env=env, capture_output=True, text=True, cwd=cwd)
    assert r.returncode == 0, r.stderr[-1500:]


@pytest.mark.parametrize("name,data,valid", [
    ("binary_classification", "binary.train", "binary.test"),
    ("regression", "regression.train", "regression.test"),
])
def test_cli_matches_python(name, data, valid, tmp_path):
    exdir = os.path.join(EXAMPLES, name)
    model_cli = str(tmp_path / "cli.txt")
    _run_cli(["config=train.conf", "output_model=" + model_cli] + DET,
             cwd=exdir)

    cfg = Config.from_cli_args(
        ["config=" + os.path.join(exdir, "train.conf")] + DET)
    params = cfg.to_dict()
    for drop in ("data", "valid", "valid_data", "output_model", "task",
                 "machine_list_filename", "config"):
        params.pop(drop, None)
    train = lgb.Dataset(os.path.join(exdir, data), params=dict(params))
    vset = lgb.Dataset(os.path.join(exdir, valid), reference=train,
                       params=dict(params))
    bst = lgb.train(params, train, num_boost_round=15, valid_sets=[vset],
                    verbose_eval=False)

    cli_trees = open(model_cli).read().split("parameters:")[0]
    py_trees = bst.model_to_string().split("parameters:")[0]
    assert cli_trees == py_trees

    # CLI predict on the valid file must equal Python predict
    preds_path = str(tmp_path / "preds.txt")
    _run_cli(["task=predict", "input_model=" + model_cli, "data=" + valid,
              "output_result=" + preds_path], cwd=exdir)
    cli_preds = np.loadtxt(preds_path)
    X = np.loadtxt(os.path.join(exdir, valid))[:, 1:]
    np.testing.assert_allclose(cli_preds, bst.predict(X), rtol=1e-12)
