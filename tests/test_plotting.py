"""Plotting API smoke tests (analog of the reference's
tests/python_package_test/test_plotting.py): each plot function renders on
an Agg canvas and returns a populated Axes/object without touching a
display."""
import numpy as np
import pytest

matplotlib = pytest.importorskip("matplotlib")
matplotlib.use("Agg")

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.plotting import (plot_importance, plot_metric,  # noqa: E402
                                   plot_split_value_histogram, plot_tree)


@pytest.fixture(scope="module")
def booster():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(800, 5))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    ds = lgb.Dataset(X, y, feature_name=[f"f{i}" for i in range(5)])
    evals = {}
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1},
                    ds, 12, valid_sets=[ds], valid_names=["train"],
                    callbacks=[lgb.record_evaluation(evals)],
                    verbose_eval=False)
    bst._evals_for_test = evals
    return bst


def test_plot_importance(booster):
    ax = plot_importance(booster)
    assert len(ax.patches) > 0
    labels = [t.get_text() for t in ax.get_yticklabels()]
    assert any(lb.startswith("f") for lb in labels)
    ax2 = plot_importance(booster, importance_type="gain", max_num_features=2)
    assert len(ax2.patches) <= 2


def test_plot_split_value_histogram(booster):
    # f0 is the strongest feature; it must have split values recorded
    ax = plot_split_value_histogram(booster, feature="f0")
    assert len(ax.patches) > 0


def test_plot_metric(booster):
    ax = plot_metric(booster._evals_for_test)
    assert len(ax.get_lines()) >= 1
    ys = ax.get_lines()[0].get_ydata()
    assert len(ys) == 12


def test_plot_tree(booster):
    try:
        ax_or_graph = plot_tree(booster, tree_index=0)
    except ImportError:
        pytest.skip("graphviz not installed")
    except Exception as e:      # dot binary missing on minimal images
        if "Executable" in type(e).__name__ or "dot" in str(e):
            pytest.skip("graphviz dot executable unavailable")
        raise
    assert ax_or_graph is not None


def test_plot_importance_empty_raises():
    bst = lgb.Booster(model_str="tree\nversion=v3\nnum_class=1\n"
                                "max_feature_idx=0\n\nend of trees\n")
    with pytest.raises(Exception):
        plot_importance(bst)
