"""The fused Pallas pair scan in the feature-/voting-parallel modes must
reproduce the XLA scan's trees (kernel in interpreter mode on CPU —
the GPU_DEBUG_COMPARE analog for the distributed scans).

Reference semantics under test: per-shard feature ownership +
SyncUpGlobalBestSplit (feature_parallel_tree_learner.cpp:33-77) and the
PV-tree local-scan/vote/selective-psum flow
(voting_parallel_tree_learner.cpp:153-344)."""
import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config


def _data(n=3000, f=10, seed=9):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 1] - 0.6 * X[:, 4] + 0.4 * X[:, 7]
         + rng.normal(size=n) * 0.4 > 0).astype(float)
    return X, y


def _tree(learner_name, scan_impl):
    from lightgbm_tpu.parallel import learners as L
    X, y = _data()
    params = {"objective": "binary", "num_leaves": 23, "verbosity": -1,
              "min_data_in_leaf": 5, "top_k": 10,
              "tpu_scan_impl": scan_impl}
    cfg = Config(dict(params))
    ds = lgb.Dataset(X, y)
    ds.construct()
    learner = getattr(L, learner_name)(cfg, ds._inner)
    # force the requested scan impl past the backend gate (the kernel runs
    # in interpreter mode on CPU), and align BOTH arms at f32: the voting
    # top-k is decided by raw gains, so an f64-XLA vs f32-kernel comparison
    # flips votes on last-ulp gain differences
    learner.grow_config = learner.grow_config._replace(
        scan_impl=scan_impl, use_dp=False, use_l1=False, use_mds=False)
    learner._sharded_grow = None
    rng = np.random.default_rng(1)
    grad = rng.normal(size=len(y)).astype(np.float32)
    hess = (rng.random(len(y)).astype(np.float32) * 0.2 + 0.05)
    n = ds._inner.num_data
    tree, _ = learner.train(jnp.asarray(grad), jnp.asarray(hess),
                            jnp.ones(n, bool))
    return tree


@pytest.mark.parametrize("mode", ["FeatureParallelTreeLearner"])
@pytest.mark.slow  # 8-device shard_map compile: ~1 min on a 2-core CPU host
def test_fused_scan_matches_xla(mode):
    # voting's fused path is experimental (vote ordering not yet
    # split-exact vs the XLA eval) and stays opt-in — see learners.py
    t_xla = _tree(mode, "xla")
    t_pal = _tree(mode, "pallas")
    k = t_xla.num_leaves
    assert t_pal.num_leaves == k
    np.testing.assert_array_equal(
        t_pal.split_feature[:k - 1], t_xla.split_feature[:k - 1])
    np.testing.assert_array_equal(
        t_pal.threshold_in_bin[:k - 1], t_xla.threshold_in_bin[:k - 1])
    np.testing.assert_allclose(
        t_pal.leaf_value[:k], t_xla.leaf_value[:k], rtol=2e-3, atol=1e-6)
