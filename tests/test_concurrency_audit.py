"""Concurrency auditor (lightgbm_tpu/analysis/concurrency_audit.py).

Contracts under test:

* the three acceptance seeded races — an unguarded shared write, a lock
  held across ``.result()``, and a two-lock ordering cycle — each flip
  the gate (``run()`` reports a failing AuditResult over a seeded
  mini-repo);
* lock-discipline semantics: one-call-level lock inheritance (the
  ``_swap_locked`` pattern), the GIL-atomic blessing table, the
  single-reference publish rule, ``__init__`` pre-publication writes,
  ``# guarded-by:`` annotations (and that a typo'd annotation is itself
  a finding), inconsistent lock sets;
* blocking-hold semantics: ``Condition.wait`` on the held lock is
  blessed, waits on foreign objects and one-call-level blocking are
  flagged, a nested thread target does not inherit its spawner's
  lexical locks;
* lock order: plain-Lock self-reentry is a self-deadlock finding,
  RLock re-entry is silent, consistent nesting stays acyclic;
* the repo self-scan is green (zero unsuppressed findings, acyclic
  order graph) and discovers the known thread roots, with the
  ``analysis::concurrency_*`` counters bumped;
* behavioral satellites: the retry watchdog's abandoned worker is
  join-with-timeout reaped on the guard's exception exit (leak counter
  when it would not die), and AsyncBatchServer.stop() racing a
  deadline flush neither hangs nor drops a request.
"""
import os
import textwrap
import threading
import time

import numpy as np
import pytest

from lightgbm_tpu.analysis import concurrency_audit as ca
from lightgbm_tpu.analysis.auditors import all_auditors
from lightgbm_tpu.analysis.config import GraftlintConfig, load_config
from lightgbm_tpu.telemetry import events


@pytest.fixture
def counters():
    prev_mode = events.mode()
    events.enable("timers")
    events.reset()
    yield events.counts_snapshot
    events.reset()
    if prev_mode == events.OFF:
        events.disable()


def _findings(src):
    return ca.check_fixture(textwrap.dedent(src))


# ---------------------------------------------------------------------
# lock discipline (JG011)


UNGUARDED_WRITE = """
    import threading

    class Server:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0
            self._thread = None

        def start(self):
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True)
            self._thread.start()

        def _loop(self):
            self._count += 1

        def submit(self):
            self._count += 1
"""


def test_unguarded_shared_write_flagged():
    hits = _findings(UNGUARDED_WRITE)
    assert any("unguarded mutation" in h and "Server._count" in h
               for h in hits)


def test_guarded_twin_silent():
    assert _findings("""
        import threading

        class Server:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0
                self._thread = None

            def start(self):
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True)
                self._thread.start()

            def _loop(self):
                with self._lock:
                    self._count += 1

            def submit(self):
                with self._lock:
                    self._count += 1
        """) == []


def test_one_call_level_lock_inheritance():
    """The _swap_locked pattern: a helper with no lexical lock whose
    EVERY call site holds the lock is analyzed as holding it."""
    assert _findings("""
        import threading

        class Registry:
            def __init__(self):
                self._lock = threading.Lock()
                self._active = None
                self._swaps = 0

            def _swap_locked(self, slot):
                self._active = slot
                self._swaps += 1

            def swap(self, slot):
                with self._lock:
                    self._swap_locked(slot)

            def load(self, slot):
                with self._lock:
                    self._swap_locked(slot)
        """) == []


def test_one_unlocked_call_site_breaks_inheritance():
    src = """
        import threading

        class Registry:
            def __init__(self):
                self._lock = threading.Lock()
                self._swaps = 0

            def _swap_locked(self, slot):
                self._swaps += 1

            def swap(self, slot):
                with self._lock:
                    self._swap_locked(slot)

            def sneak(self, slot):
                self._swap_locked(slot)
        """
    assert any("Registry._swaps" in h for h in _findings(src))


def test_gil_atomic_deque_append_blessed_dict_rmw_not():
    """deque.append is one bytecode under the GIL (blessed); a dict
    subscript += is a read-modify-write (flagged)."""
    src = """
        import threading
        from collections import deque

        _lock = threading.Lock()
        _ring = deque(maxlen=64)
        _totals = {}

        def sink(ev):
            _ring.append(ev)

        def bump(k):
            _totals[k] += 1

        def install(cb):
            cb(sink)
    """
    hits = _findings(src)
    assert not any("_ring" in h for h in hits)
    assert any("_totals" in h for h in hits)


def test_single_reference_publish_blessed():
    assert _findings("""
        import threading

        class Holder:
            def __init__(self):
                self._lock = threading.Lock()
                self._model = None

            def publish(self, model):
                self._model = model
        """) == []


def test_guarded_by_annotation_blesses_and_typo_is_finding():
    good = """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._hits = 0

            def bump(self):
                self._hits += 1    # guarded-by: _lock
        """
    assert _findings(good) == []
    typo = good.replace("guarded-by: _lock", "guarded-by: _lokc")
    hits = _findings(typo)
    assert any("unknown lock/root" in h for h in hits)


def test_inconsistent_lock_sets_flagged():
    hits = _findings("""
        import threading

        class S:
            def __init__(self):
                self._lock_a = threading.Lock()
                self._lock_b = threading.Lock()
                self._n = 0

            def via_a(self):
                with self._lock_a:
                    self._n += 1

            def via_b(self):
                with self._lock_b:
                    self._n += 1
        """)
    assert any("inconsistent lock sets" in h for h in hits)


# ---------------------------------------------------------------------
# blocking-hold (JG012)


HOLD_RESULT = """
    import threading

    class Pool:
        def __init__(self):
            self._lock = threading.Lock()
            self._done = 0

        def flush(self, fut):
            with self._lock:
                out = fut.result()
                self._done += 1
            return out
"""


def test_lock_held_across_result_flagged():
    hits = _findings(HOLD_RESULT)
    assert any("blocking" in h and "result" in h for h in hits)


def test_blocking_after_release_silent():
    assert _findings("""
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self._done = 0

            def flush(self, fut):
                out = fut.result()
                with self._lock:
                    self._done += 1
                return out
        """) == []


def test_condition_wait_on_held_lock_blessed():
    assert _findings("""
        import threading

        class Q:
            def __init__(self):
                self._cond = threading.Condition()
                self._items = []

            def take(self):
                with self._cond:
                    while not self._items:
                        self._cond.wait(timeout=0.01)
                    return self._items.pop()
        """) == []


def test_wait_on_foreign_object_under_lock_flagged():
    hits = _findings("""
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()

            def drain(self, worker):
                with self._lock:
                    worker.join()
        """)
    assert any("blocking" in h and "join" in h for h in hits)


def test_one_call_level_blocking_propagates():
    hits = _findings("""
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def _slow(self):
                time.sleep(0.5)

            def tick(self):
                with self._lock:
                    self._slow()
        """)
    assert any("whose body performs a blocking operation" in h
               for h in hits)


def test_nested_thread_target_does_not_inherit_spawner_locks():
    """The retry-watchdog shape: `run` is defined inside a function
    that may hold a lock at spawn time, but executes on its own thread
    with nothing held — its sleep is not a blocking-hold."""
    assert _findings("""
        import threading
        import time

        _lock = threading.Lock()

        def call_with_deadline(fn):
            result = {}

            def run():
                time.sleep(0.01)
                result["value"] = fn()

            with _lock:
                worker = threading.Thread(target=run, daemon=True)
                worker.start()
            worker.join()
            return result.get("value")
        """) == []


# ---------------------------------------------------------------------
# lock order


TWO_LOCK_CYCLE = """
    import threading

    _lock_a = threading.Lock()
    _lock_b = threading.Lock()

    def fwd():
        with _lock_a:
            with _lock_b:
                pass

    def rev():
        with _lock_b:
            with _lock_a:
                pass
"""


def test_two_lock_ordering_cycle_flagged():
    hits = _findings(TWO_LOCK_CYCLE)
    assert any("lock-acquisition-order cycle" in h for h in hits)


def test_consistent_nesting_is_acyclic():
    assert _findings("""
        import threading

        _lock_a = threading.Lock()
        _lock_b = threading.Lock()

        def one():
            with _lock_a:
                with _lock_b:
                    pass

        def two():
            with _lock_a:
                with _lock_b:
                    pass
        """) == []


def test_plain_lock_self_reentry_is_self_deadlock():
    hits = _findings("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    with self._lock:
                        pass
        """)
    assert any("self-deadlock" in h for h in hits)


def test_rlock_reentry_silent():
    assert _findings("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    with self._lock:
                        pass
        """) == []


def test_module_without_locks_or_threads_out_of_scope():
    """Owning a lock or spawning a thread is how code declares
    concurrent intent; a plain single-threaded module is not audited."""
    assert _findings("""
        _cache = {}

        def put(k, v):
            _cache[k] = v

        def bump(k):
            _cache[k] += 1
        """) == []


# ---------------------------------------------------------------------
# the gate: seeded mini-repos flip run(), the real repo stays green


def _seeded_config(tmp_path, source):
    srv = tmp_path / "srv"
    srv.mkdir()
    (srv / "seeded.py").write_text(textwrap.dedent(source))
    return GraftlintConfig(root=str(tmp_path),
                           concurrency_paths=["srv/"])


@pytest.mark.parametrize("source,audit_name", [
    (UNGUARDED_WRITE, "concurrency_discipline"),
    (HOLD_RESULT, "concurrency_blocking_hold"),
    (TWO_LOCK_CYCLE, "concurrency_lock_order"),
])
def test_seeded_race_flips_gate(tmp_path, source, audit_name):
    results = {r.name: r for r in ca.run(_seeded_config(tmp_path,
                                                        source))}
    assert not results[audit_name].ok
    assert results[audit_name].detail


def test_repo_self_scan_green_and_counters(counters):
    cfg = load_config()
    results = {r.name: r for r in ca.run(cfg)}
    assert set(results) == {"concurrency_discipline",
                            "concurrency_blocking_hold",
                            "concurrency_lock_order"}
    assert all(r.ok for r in results.values()), \
        {n: r.detail for n, r in results.items() if not r.ok}
    counts = counters()
    assert counts.get("analysis::concurrency_roots", 0) >= 2
    assert counts.get("analysis::shared_sites", 0) > 0
    assert "analysis::unguarded" not in counts
    assert "analysis::hold_blocking" not in counts


def test_repo_trace_discovers_known_roots():
    trace = ca.extract_trace(load_config())
    assert set(trace) == {"roots", "shared_sites", "lock_order",
                          "findings"}
    roots = {(r["name"], r["kind"]) for r in trace["roots"]}
    assert ("AsyncBatchServer._loop", "thread") in roots
    assert ("_call_with_deadline.run", "thread") in roots
    # the flight-recorder sinks escape as callbacks into events.py
    assert ("_span_sink", "callback") in roots
    assert trace["lock_order"]["cycles"] == []
    assert trace["findings"] == []
    # the serving loop is the condition-wait service loop
    loop = next(r for r in trace["roots"]
                if r["name"] == "AsyncBatchServer._loop")
    assert loop["cond_wait"]


def test_registered_in_auditor_registry():
    assert all_auditors()["concurrency"] is ca


def test_run_accepts_precomputed_artifact(tmp_path):
    cfg = _seeded_config(tmp_path, UNGUARDED_WRITE)
    art = ca.compute_artifact(cfg)
    results = {r.name: r for r in ca.run(cfg, artifact=art)}
    assert not results["concurrency_discipline"].ok


def test_inline_suppression_blesses_gate(tmp_path):
    suppressed = UNGUARDED_WRITE.replace(
        "self._count += 1\n",
        "self._count += 1  # graftlint: disable=JG011\n")
    assert suppressed != UNGUARDED_WRITE
    results = {r.name: r for r in ca.run(_seeded_config(tmp_path,
                                                        suppressed))}
    assert results["concurrency_discipline"].ok


# ---------------------------------------------------------------------
# satellite: retry watchdog shutdown discipline


def test_watchdog_leak_counted_on_exception_exit(counters, monkeypatch):
    """A guard exiting by exception must join-with-timeout its
    abandoned worker; one that will not die inside the grace is counted
    as a leak."""
    from lightgbm_tpu.resilience import retry
    from lightgbm_tpu.utils.log import LightGBMError
    monkeypatch.setattr(retry, "_REAP_GRACE_S", 0.01)
    release = threading.Event()
    old = retry._POLICY
    retry._POLICY = retry.RetryPolicy(timeout_s=0.05, retries=0,
                                      backoff_s=0.01)
    try:
        with pytest.raises(LightGBMError):
            retry.guard("allgather:leak", release.wait, 30.0)
        counts = counters()
        assert counts.get(retry.C_THREAD_LEAK, 0) >= 1
    finally:
        release.set()       # let the leaked worker exit promptly
        retry._POLICY = old


def test_watchdog_reaped_when_it_finishes(counters, monkeypatch):
    """A worker that finishes shortly after the deadline is joined by
    the grace sweep — no leak counter, no lingering thread."""
    from lightgbm_tpu.resilience import retry
    from lightgbm_tpu.utils.log import LightGBMError
    monkeypatch.setattr(retry, "_REAP_GRACE_S", 5.0)
    old = retry._POLICY
    retry._POLICY = retry.RetryPolicy(timeout_s=0.05, retries=0,
                                      backoff_s=0.01)
    try:
        with pytest.raises(LightGBMError):
            retry.guard("allgather:slowpoke", time.sleep, 0.3)
        counts = counters()
        assert retry.C_THREAD_LEAK not in counts
        assert not [t for t in threading.enumerate()
                    if t.name.startswith("lgbtpu-collective-")
                    and t.is_alive()]
    finally:
        retry._POLICY = old


# ---------------------------------------------------------------------
# satellite: stop() racing a deadline flush on AsyncBatchServer


@pytest.fixture(scope="module")
def small_model():
    import lightgbm_tpu as lgb
    rng = np.random.default_rng(7)
    X = (rng.integers(0, 16, size=(600, 6)) / 4.0).astype(np.float64)
    y = (X[:, 0] - X[:, 2] > 0.5).astype(float)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "min_data_in_leaf": 5, "seed": 0, "deterministic": True}
    booster = lgb.train(dict(params), lgb.Dataset(X, y, params=params),
                        5, verbose_eval=False)
    return booster, X


def test_stop_during_deadline_flush_race(small_model):
    """stop(drain=True) issued while sub-bucket requests sit inside
    their coalescing window: the shutdown path and the deadline flush
    race on _cond, and every request must still be answered — the
    zero-drop guarantee covers shutdown (and nothing deadlocks)."""
    from lightgbm_tpu.serving import AsyncBatchServer
    booster, X = small_model
    pred = booster._booster.device_predictor()
    ref = booster.predict(X[:7], raw_score=True)
    for _ in range(5):
        server = AsyncBatchServer(pred, min_batch=64, max_batch=256,
                                  max_wait_ms=40.0).start()
        fut = server.submit(X[:7], raw_score=True)
        # land stop() inside the 40ms coalescing window, so the
        # deadline flush and the drain path contend for _cond
        stopper = threading.Thread(target=server.stop)
        stopper.start()
        out = fut.result(timeout=10.0)
        stopper.join(timeout=10.0)
        assert not stopper.is_alive(), "stop() deadlocked"
        np.testing.assert_array_equal(out, ref)


def test_stop_without_drain_fails_pending_cleanly(small_model):
    from lightgbm_tpu.serving import AsyncBatchServer, ServingError
    booster, X = small_model
    pred = booster._booster.device_predictor()
    server = AsyncBatchServer(pred, min_batch=64, max_batch=256,
                              max_wait_ms=250.0).start()
    futs = [server.submit(X[i:i + 3], raw_score=True) for i in range(4)]
    server.stop(drain=False)
    # every future resolves (value or ServingError) — nothing hangs
    for f in futs:
        try:
            f.result(timeout=10.0)
        except ServingError:
            pass
    with pytest.raises(ServingError):
        server.submit(X[:2])


# ---------------------------------------------------------------------
# config scoping


def test_concurrency_paths_config_round_trip():
    cfg = load_config()
    assert any("serving" in p for p in cfg.concurrency_paths)
    assert any("telemetry" in p for p in cfg.concurrency_paths)
    files = ca._audited_files(cfg)
    assert "lightgbm_tpu/serving/server.py" in files
    assert "lightgbm_tpu/resilience/retry.py" in files
    assert all(os.path.isfile(os.path.join(cfg.root, f))
               for f in files)
