"""Persistent-payload fast path under sharding: the K-iteration persist
scan on an 8-device CPU mesh (data-parallel learner, histogram-plane psum
inside the grow loop) must reproduce the single-payload persist scan tree
for tree (reference contract: data_parallel_tree_learner.cpp:163-250 —
reduce-scattered histograms give every rank identical split decisions).

tpu_persist_scan=force engages the XLA kernel emulation
(ops/grow_persist.make_xla_split_pass) off-TPU; both sides run the same
emulated kernels, so differences can only come from the sharding wiring
under test (per-shard payloads, shard-local geometry, psum'd stats).
"""
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb

# the persist grower compiles large multi-stage programs (and most tests
# here shard them over the 8-virtual-device mesh): 7-140s each on the
# 2-core CPU CI host, ~14 min for the file — slow tier, not tier-1
pytestmark = pytest.mark.slow


N = 6144          # 8 shards x 768 rows
F = 6
ROUNDS = 16       # exactly one fused persist batch


def _data(seed=7):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(N, F))
    y = (X[:, 0] - 0.7 * X[:, 2] + 0.4 * X[:, 4]
         + rng.normal(size=N) * 0.25 > 0).astype(float)
    return X, y


def _train(X, y, learner, extra=None):
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 10, "max_bin": 63, "learning_rate": 0.2,
              "tpu_persist_scan": "force", "tree_learner": learner}
    if extra:
        params.update(extra)
    bst = lgb.train(params, lgb.Dataset(X, y), ROUNDS, verbose_eval=False)
    tl = bst._booster.tree_learner
    assert getattr(tl, "_persist_carry", None) is not None, \
        "persist fast path did not engage for tree_learner=%s" % learner
    return bst


def _tree_tuples(bst):
    """(structure, values): split features/thresholds/counts pinned exactly;
    leaf/internal values compared with f32 tolerance (psum of per-shard f32
    histogram partials rounds differently than a whole-data sum)."""
    model = bst.dump_model()
    if isinstance(model, str):
        model = json.loads(model)
    structure, values = [], []
    for t in model["tree_info"]:
        def walk(node):
            if "split_feature" in node:
                structure.append((node["split_feature"],
                                  round(float(node["threshold"]), 9),
                                  node["internal_count"]))
                walk(node["left_child"])
                walk(node["right_child"])
            else:
                structure.append(("leaf", node["leaf_count"]))
                values.append(float(node["leaf_value"]))
        walk(t["tree_structure"])
    return structure, np.asarray(values)


def test_persist_sharded_matches_persist_serial():
    assert len(jax.devices()) >= 8, "conftest provides 8 virtual devices"
    X, y = _data()
    bst_serial = _train(X, y, "serial")
    bst_sharded = _train(X, y, "data")
    s_serial, v_serial = _tree_tuples(bst_serial)
    s_sharded, v_sharded = _tree_tuples(bst_sharded)
    assert s_serial == s_sharded
    np.testing.assert_allclose(v_serial, v_sharded, rtol=2e-5, atol=2e-6)
    p1 = bst_serial.predict(X[:512])
    p2 = bst_sharded.predict(X[:512])
    np.testing.assert_allclose(p1, p2, rtol=1e-4, atol=1e-6)


def test_persist_matches_v1_grower():
    """The persist fast path (XLA kernel emulation) reproduces the v1
    masked/partitioned grower's trees: same splits and counts; values to
    f32 tolerance (v1 accumulates in f64 on CPU)."""
    X, y = _data(seed=23)
    base = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 10, "max_bin": 63, "learning_rate": 0.2}
    bst_p = lgb.train({**base, "tpu_persist_scan": "force"},
                      lgb.Dataset(X, y), ROUNDS, verbose_eval=False)
    assert getattr(bst_p._booster.tree_learner, "_persist_carry",
                   None) is not None
    bst_v1 = lgb.train({**base, "tpu_persist_scan": "off"},
                       lgb.Dataset(X, y), ROUNDS, verbose_eval=False)
    s_p, v_p = _tree_tuples(bst_p)
    s_v1, v_v1 = _tree_tuples(bst_v1)
    assert s_p == s_v1
    np.testing.assert_allclose(v_p, v_v1, rtol=1e-3, atol=1e-5)


def _root_counts(bst):
    model = bst.dump_model()
    if isinstance(model, str):
        model = json.loads(model)
    out = []
    for t in model["tree_info"]:
        node = t["tree_structure"]
        out.append(node.get("internal_count", node.get("leaf_count", 0)))
    return np.asarray(out)


BAG = {"bagging_fraction": 0.8, "bagging_freq": 5}


def test_persist_bagging_counts_and_quality():
    """Device-side bagging on the persist path: root counts track the
    bagging fraction (exact in-bag count feeds the root statistics) and
    the model still learns."""
    X, y = _data(seed=31)
    bst = _train(X, y, "serial", extra=BAG)
    rc = _root_counts(bst)
    assert np.all(np.abs(rc / N - 0.8) < 0.05), rc / N
    acc = ((bst.predict(X) > 0.5) == y).mean()
    assert acc > 0.85, acc


def test_persist_bagging_sharded_matches_serial():
    """Bag masks hash GLOBAL row ids, so the sharded persist run redraws
    the identical bag and reproduces the serial persist trees."""
    X, y = _data(seed=37)
    bst_serial = _train(X, y, "serial", extra=BAG)
    bst_sharded = _train(X, y, "data", extra=BAG)
    s1, v1 = _tree_tuples(bst_serial)
    s2, v2 = _tree_tuples(bst_sharded)
    assert s1 == s2
    np.testing.assert_allclose(v1, v2, rtol=2e-5, atol=2e-6)


def test_persist_goss():
    """Device-side GOSS: warmup iterations keep every row
    (goss.hpp:126-131), sampled iterations keep ~(top_rate+other_rate) with
    the amplification preserving learning quality."""
    X, y = _data(seed=41)
    # learning_rate 0.2 -> 5 warmup iterations of the 16
    bst = _train(X, y, "serial",
                 extra={"boosting": "goss", "top_rate": 0.2,
                        "other_rate": 0.1})
    rc = _root_counts(bst)
    assert np.all(rc[:5] == N), rc[:5]
    frac = rc[5:] / N
    assert np.all(np.abs(frac - 0.3) < 0.05), frac
    acc = ((bst.predict(X) > 0.5) == y).mean()
    assert acc > 0.85, acc


def _data_mc(seed=51, k=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(N, F))
    y = ((X[:, 0] > 0.4).astype(int) + (X[:, 2] > -0.2).astype(int))
    return X, np.clip(y, 0, k - 1).astype(float)


@pytest.mark.parametrize("obj", ["multiclass", "multiclassova"])
def test_persist_multiclass_matches_v1(obj):
    """K-trees-per-iteration on the persist path (per-class snapshot
    gradients) reproduces the v1 grower's trees."""
    X, y = _data_mc()
    base = {"objective": obj, "num_class": 3, "num_leaves": 8,
            "verbosity": -1, "min_data_in_leaf": 10, "max_bin": 63,
            "learning_rate": 0.2}
    bst_p = lgb.train({**base, "tpu_persist_scan": "force"},
                      lgb.Dataset(X, y), ROUNDS, verbose_eval=False)
    assert getattr(bst_p._booster.tree_learner, "_persist_carry",
                   None) is not None, "persist did not engage for %s" % obj
    bst_v1 = lgb.train({**base, "tpu_persist_scan": "off"},
                       lgb.Dataset(X, y), ROUNDS, verbose_eval=False)
    assert bst_p.num_trees() == bst_v1.num_trees() == ROUNDS * 3
    # the first iteration matches to f32 precision; past that, the f32
    # persist scan's hessian-derived count recovery (multiclass hessians
    # 2p(1-p) sit near zero) can flip a min_data gate the f64 v1 scan
    # accepts — the reference GPU learner's gpu_use_dp=false trade — so
    # the full models compare by quality
    p_early = bst_p.predict(X[:512], num_iteration=1)
    v_early = bst_v1.predict(X[:512], num_iteration=1)
    np.testing.assert_allclose(p_early, v_early, rtol=1e-4, atol=1e-6)
    p1 = bst_p.predict(X)
    p2 = bst_v1.predict(X)
    assert p1.shape == (N, 3)
    yi = y.astype(int)
    ll_p = -np.mean(np.log(np.clip(p1[np.arange(N), yi], 1e-12, 1)))
    ll_v = -np.mean(np.log(np.clip(p2[np.arange(N), yi], 1e-12, 1)))
    assert abs(ll_p - ll_v) < 5e-3, (ll_p, ll_v)
    acc = (np.argmax(p1, axis=1) == yi).mean()
    assert acc > 0.8, acc


def test_persist_sharded_scores_row_ordered():
    """finalize_scores under shard_map returns globally row-ordered scores
    (global row ids with the shard offset subtracted; contiguous row
    shards)."""
    X, y = _data(seed=11)
    bst = _train(X, y, "data")
    inner = bst._booster
    inner._materialize_pending()
    # staged score == sum of tree outputs in row order
    staged = np.asarray(inner.train_score.score_device(0))
    pred_raw = bst.predict(X, raw_score=True)
    # order is the point here: a misplaced shard/rid would be off by O(1);
    # the payload carries scores in f32, predict sums trees in f64
    np.testing.assert_allclose(staged, pred_raw, rtol=1e-4, atol=1e-5)


def test_persist_f64_state_matches_f32(monkeypatch):
    """Above EXACT_F32_ROWS the persist leaf state switches to f64 for
    exact counts (the 2^24 cap lift); at small n the two dtypes must
    agree (same trees, counts exact either way)."""
    import lightgbm_tpu.ops.grow_persist as GP
    X, y = _data(seed=61)
    base = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 10, "max_bin": 63,
            "tpu_persist_scan": "force"}
    bst32 = lgb.train(dict(base), lgb.Dataset(X, y), ROUNDS,
                      verbose_eval=False)
    monkeypatch.setattr(GP, "EXACT_F32_ROWS", 1024)   # force f64 state
    bst64 = lgb.train(dict(base), lgb.Dataset(X, y), ROUNDS,
                      verbose_eval=False)
    assert getattr(bst64._booster.tree_learner, "_persist_carry",
                   None) is not None
    s32, v32 = _tree_tuples(bst32)
    s64, v64 = _tree_tuples(bst64)
    assert s32 == s64
    np.testing.assert_allclose(v32, v64, rtol=1e-5, atol=1e-7)


def _data_rank(seed=71, docs=48):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(N, F))
    sig = X[:, 0] - 0.6 * X[:, 2] + rng.normal(size=N) * 0.5
    nq = N // docs
    s = sig.reshape(nq, docs)
    q = np.quantile(s, [0.5, 0.8, 0.95], axis=1)
    lab = ((s > q[0][:, None]).astype(int) + (s > q[1][:, None])
           + (s > q[2][:, None]))
    group = np.full(nq, docs, np.int32)
    return X, lab.reshape(-1).astype(float), group


def test_persist_lambdarank_pos_mode_matches_row_mode(monkeypatch):
    """Payload-position lambdarank gradients (one scatter through the
    row-id map, ops/grow_persist.fill_grad_pos) see exactly the score
    values the row-order round-trip mode sees, so the trees must match
    bit-for-bit on CPU."""
    X, y, group = _data_rank()
    base = {"objective": "lambdarank", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 10, "max_bin": 63, "learning_rate": 0.2,
            "tpu_persist_scan": "force"}

    def run():
        bst = lgb.train(dict(base), lgb.Dataset(X, y, group=group),
                        ROUNDS, verbose_eval=False)
        assert getattr(bst._booster.tree_learner, "_persist_carry",
                       None) is not None, "persist did not engage"
        return bst

    bst_pos = run()
    obj = bst_pos._booster.objective
    assert obj.persist_grad_mode() == "pos"
    from lightgbm_tpu.objectives.rank import LambdarankNDCG
    monkeypatch.setattr(LambdarankNDCG, "payload_pos_fn",
                        lambda self: None)
    bst_row = run()
    assert bst_row._booster.objective.persist_grad_mode() == "row"
    s_pos, v_pos = _tree_tuples(bst_pos)
    s_row, v_row = _tree_tuples(bst_row)
    assert s_pos == s_row
    np.testing.assert_allclose(v_pos, v_row, rtol=1e-6, atol=1e-9)
    # and the model actually ranks: training NDCG@5 beats random order
    from lightgbm_tpu.metrics.dcg import (cal_dcg_at_k, cal_max_dcg_at_k,
                                          default_label_gain)
    lg = default_label_gain()
    pred = bst_pos.predict(X)
    nd = []
    off = 0
    for g in group:
        lab = y[off:off + g]
        sc = pred[off:off + g]
        off += g
        mx = cal_max_dcg_at_k(5, lab, lg)
        if mx > 0:
            nd.append(cal_dcg_at_k(5, lab, sc, lg) / mx)
    assert np.mean(nd) > 0.75, np.mean(nd)


def test_persist_mosaic_kernels_interpret_match_emulation(monkeypatch):
    """The production TPU kernel path (split_pass with _skip_hist +
    make_seg_hist post-partition histogram) run in Pallas INTERPRETER mode
    must reproduce the XLA-emulation trees — covers the Mosaic wiring
    (chunk DMA alignment rolls, lane masks, FIFO drains, seg_hist
    start/len) that the emulation-only tests never touch."""
    from lightgbm_tpu.ops.pallas_compat import dynamic_grid_interpret_ok
    from lightgbm_tpu.treelearner.serial import SerialTreeLearner
    if not dynamic_grid_interpret_ok():
        # jax 0.4.x state discharge rejects the dynamic-grid kernels in
        # interpret mode (make_persist_grower downgrades to the XLA
        # emulation loudly); emu-vs-emu here would assert nothing
        pytest.skip("pallas interpret mode cannot discharge the "
                    "dynamic-grid split kernels on this jax (< 0.5)")
    X, y = _data(seed=97)
    n_small, rounds = 2048, ROUNDS   # >= the fused batch size, so the
    Xs, ys = X[:n_small], y[:n_small]   # persist driver engages
    base = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
            "min_data_in_leaf": 10, "max_bin": 31, "learning_rate": 0.2,
            "tpu_persist_scan": "force"}
    bst_emu = lgb.train(dict(base), lgb.Dataset(Xs, ys), rounds,
                        verbose_eval=False)
    monkeypatch.setattr(SerialTreeLearner, "_persist_kernel_mode",
                        staticmethod(lambda: ("pallas", True)))
    bst_mos = lgb.train(dict(base), lgb.Dataset(Xs, ys), rounds,
                        verbose_eval=False)
    assert getattr(bst_mos._booster.tree_learner, "_persist_carry",
                   None) is not None
    s_e, v_e = _tree_tuples(bst_emu)
    s_m, v_m = _tree_tuples(bst_mos)
    assert s_e == s_m
    np.testing.assert_allclose(v_e, v_m, rtol=1e-4, atol=1e-6)
    # early-stopping trees (min_data exhausts the splits before num_leaves)
    # exercise the ZERO-GRID split_pass: the payload must pass through
    # unharmed even though no chunk steps run (interpret has no aliasing)
    stop = {**base, "num_leaves": 31, "min_data_in_leaf": 600}
    bst_s = lgb.train(dict(stop), lgb.Dataset(Xs, ys), rounds,
                      verbose_eval=False)
    s_s, _ = _tree_tuples(bst_s)
    nl = sum(1 for e in s_s if e[0] == "leaf")
    assert nl < rounds * 31, "expected early-stopped trees"
    monkeypatch.undo()
    bst_se = lgb.train(dict(stop), lgb.Dataset(Xs, ys), rounds,
                       verbose_eval=False)
    s_se, _ = _tree_tuples(bst_se)
    assert s_s == s_se


def test_persist_voting_full_vote_matches_data_parallel():
    """Voting-parallel on the sharded persist driver: with 2*top_k >= F
    every feature wins the vote, the selective psum covers the whole
    histogram, and the trees must match the data-parallel persist run
    (PV-tree exactness condition, voting_parallel_tree_learner.cpp:153)."""
    X, y = _data(seed=43)
    bst_data = _train(X, y, "data")
    bst_vote = _train(X, y, "voting", extra={"top_k": F})
    s_d, v_d = _tree_tuples(bst_data)
    s_v, v_v = _tree_tuples(bst_vote)
    assert s_d == s_v
    np.testing.assert_allclose(v_d, v_v, rtol=2e-5, atol=2e-6)


def test_persist_voting_small_vote_learns():
    """top_k below F engages the real PV-tree approximation: the model
    still learns (the reference makes the same accuracy trade)."""
    X, y = _data(seed=47)
    bst = _train(X, y, "voting", extra={"top_k": 2})
    acc = ((bst.predict(X) > 0.5) == y).mean()
    assert acc > 0.85, acc


def test_persist_weighted_matches_v1():
    """Sample weights ride the payload as one extra row and multiply into
    the gradients after the objective (grow_persist._apply_weight): the
    persist trees must reproduce the v1 weighted grower's."""
    X, y = _data(seed=53)
    rng = np.random.default_rng(8)
    w = rng.uniform(0.25, 4.0, N)
    base = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 10, "max_bin": 63, "learning_rate": 0.2}
    ds_p = lgb.Dataset(X, y, weight=w)
    bst_p = lgb.train({**base, "tpu_persist_scan": "force"}, ds_p,
                      ROUNDS, verbose_eval=False)
    assert getattr(bst_p._booster.tree_learner, "_persist_carry",
                   None) is not None, "weighted persist did not engage"
    bst_v1 = lgb.train({**base, "tpu_persist_scan": "off"},
                       lgb.Dataset(X, y, weight=w), ROUNDS,
                       verbose_eval=False)
    s_p, v_p = _tree_tuples(bst_p)
    s_v1, v_v1 = _tree_tuples(bst_v1)
    assert s_p == s_v1
    np.testing.assert_allclose(v_p, v_v1, rtol=1e-3, atol=1e-5)


def test_persist_weighted_sharded_and_lambdarank():
    """Weighted runs on the sharded persist path and weighted lambdarank
    through the payload-position mode (weights multiply the lambdas,
    rank_objective.hpp:165-170)."""
    X, y = _data(seed=59)
    rng = np.random.default_rng(9)
    w = rng.uniform(0.5, 2.0, N)
    bst_s = _train_weighted(X, y, w, "serial")
    bst_d = _train_weighted(X, y, w, "data")
    s1, v1 = _tree_tuples(bst_s)
    s2, v2 = _tree_tuples(bst_d)
    assert s1 == s2
    # varied weights widen the f32 psum-vs-whole-sum rounding slightly
    np.testing.assert_allclose(v1, v2, rtol=1e-4, atol=2e-6)
    # weighted lambdarank: pos mode == row-order mode bit for bit (both
    # multiply weights in f64 before the f32 cast; the payload weight
    # row is NOT applied in pos mode, so weights act exactly once)
    Xr, yr, group = _data_rank(seed=61)
    wr = np.repeat(rng.uniform(0.5, 2.0, len(group)), group)
    base = {"objective": "lambdarank", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 10, "max_bin": 63, "learning_rate": 0.2,
            "tpu_persist_scan": "force"}

    def run_rank():
        bst = lgb.train(dict(base),
                        lgb.Dataset(Xr, yr, group=group, weight=wr),
                        ROUNDS, verbose_eval=False)
        assert getattr(bst._booster.tree_learner, "_persist_carry",
                       None) is not None
        return bst

    bst_pos = run_rank()
    assert bst_pos._booster.objective.persist_grad_mode() == "pos"
    from lightgbm_tpu.objectives.rank import LambdarankNDCG
    import pytest as _pytest
    mp = _pytest.MonkeyPatch()
    try:
        mp.setattr(LambdarankNDCG, "payload_pos_fn", lambda self: None)
        bst_row = run_rank()
        assert bst_row._booster.objective.persist_grad_mode() == "row"
    finally:
        mp.undo()
    s_p, v_p = _tree_tuples(bst_pos)
    s_r, v_r = _tree_tuples(bst_row)
    assert s_p == s_r
    np.testing.assert_allclose(v_p, v_r, rtol=1e-6, atol=1e-9)


def _train_weighted(X, y, w, learner):
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 10, "max_bin": 63, "learning_rate": 0.2,
              "tpu_persist_scan": "force", "tree_learner": learner}
    bst = lgb.train(params, lgb.Dataset(X, y, weight=w), ROUNDS,
                    verbose_eval=False)
    assert getattr(bst._booster.tree_learner, "_persist_carry",
                   None) is not None
    return bst


def _data_sparse_bundled(seed=67, n=N, f_dense=3, f_sparse=9):
    """Mostly-zero indicator features that EFB greedily bundles into
    shared byte columns (multi-feature groups with the bin-0 sentinel)."""
    rng = np.random.default_rng(seed)
    Xd = rng.normal(size=(n, f_dense))
    # mutually exclusive indicators (a one-hot-encoded categorical):
    # zero conflicts, so greedy bundling packs them into one group
    Xs = np.zeros((n, f_sparse))
    owner = rng.integers(0, f_sparse * 3, n)     # most rows all-zero
    for j in range(f_sparse):
        hit = owner == j
        Xs[hit, j] = rng.uniform(1.0, 4.0, hit.sum())
    X = np.concatenate([Xd, Xs], axis=1)
    y = (X[:, 0] - 0.5 * X[:, 1] + 0.8 * (X[:, f_dense] > 0)
         + 0.6 * (X[:, f_dense + 1] > 0)
         + rng.normal(size=n) * 0.3 > 0.4).astype(float)
    return X, y


def test_persist_efb_bundled_matches_v1():
    """EFB-bundled datasets ride the persist path: the split kernel
    decodes the group byte through the feature's [LS, LE) range, the scan
    reads windowed group blocks, and the in-eval FixHistogram repairs the
    most_freq bins — trees must match the v1 grower's."""
    X, y = _data_sparse_bundled()
    base = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 10, "max_bin": 63, "learning_rate": 0.2}
    ds = lgb.Dataset(X, y)
    bst_p = lgb.train({**base, "tpu_persist_scan": "force"}, ds,
                      ROUNDS, verbose_eval=False)
    inner = bst_p._booster.tree_learner.dataset
    assert len(inner.groups) < inner.num_features, \
        "expected EFB bundles in this synthetic"
    assert bool(np.any(inner.needs_fix))
    assert getattr(bst_p._booster.tree_learner, "_persist_carry",
                   None) is not None, "bundled persist did not engage"
    bst_v1 = lgb.train({**base, "tpu_persist_scan": "off"},
                       lgb.Dataset(X, y), ROUNDS, verbose_eval=False)
    # early iterations match exactly; past that the f32 FixHistogram
    # residual (child_total - window_sum, cancellation-prone) can flip a
    # near-tie the f64 v1 fix resolves the other way — the same
    # gpu_use_dp=false trade the multiclass test documents. Full models
    # compare by fit quality.
    p_early = bst_p.predict(X[:1024], num_iteration=4)
    v_early = bst_v1.predict(X[:1024], num_iteration=4)
    np.testing.assert_allclose(p_early, v_early, rtol=1e-4, atol=1e-6)
    acc_p = ((bst_p.predict(X) > 0.5) == y).mean()
    acc_v = ((bst_v1.predict(X) > 0.5) == y).mean()
    assert abs(acc_p - acc_v) < 0.01, (acc_p, acc_v)
    assert acc_p > 0.8, acc_p


def test_persist_efb_sharded_matches_serial():
    """Bundled persist under the 8-device mesh reproduces serial persist."""
    X, y = _data_sparse_bundled(seed=71)
    bst_s = _train(X, y, "serial")
    bst_d = _train(X, y, "data")
    s1, v1 = _tree_tuples(bst_s)
    s2, v2 = _tree_tuples(bst_d)
    assert s1 == s2
    np.testing.assert_allclose(v1, v2, rtol=1e-4, atol=2e-6)


def test_persist_goss_sharded_matches_serial():
    """Sharded GOSS redraws the serial bag exactly: the top-rate threshold
    is the GLOBAL k-th largest |g*h| via radix select on psum'd counts,
    and the keep/amplify draws hash global row ids."""
    X, y = _data(seed=73)
    extra = {"boosting": "goss", "top_rate": 0.2, "other_rate": 0.1}
    bst_s = _train(X, y, "serial", extra=extra)
    bst_d = _train(X, y, "data", extra=extra)
    # early predictions match exactly (identical threshold + draws); deep
    # into the run a row whose |g*h| sits at the threshold can flip on
    # the f32 psum-vs-whole-sum score drift, so full models compare by
    # quality
    p_s = bst_s.predict(X[:1024], num_iteration=8)
    p_d = bst_d.predict(X[:1024], num_iteration=8)
    np.testing.assert_allclose(p_s, p_d, rtol=1e-4, atol=1e-6)
    acc_s = ((bst_s.predict(X) > 0.5) == y).mean()
    acc_d = ((bst_d.predict(X) > 0.5) == y).mean()
    assert abs(acc_s - acc_d) < 0.01, (acc_s, acc_d)
    assert acc_s > 0.85, acc_s
