"""Expo-shaped EFB regression: the bundle fast path must ENGAGE and match.

BENCH_r05 measured the Expo shape at 0.23x the reference CPU anchor; the
bundle-native rebuild (block scan + in-pass smaller-child histogram +
cached window masks) is only a win if the fast path actually takes these
datasets. The regression test pins, via telemetry counters, that a small
Expo-shaped training runs ENTIRELY on the persist driver (zero v1 trees,
the block-scan grower built) while predictions still match the v1 grower.
The profile-CLI smoke test keeps `python -m lightgbm_tpu.profile --shape
expo` working on CPU so the bench's phase breakdown stays reproducible
without the full bench.
"""
import json

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.data.synth import make_expo_like
from lightgbm_tpu.telemetry import events


def _expo_small(n=6144):
    X, y = make_expo_like(n_rows=n, seed=3)
    return X, y


@pytest.mark.slow  # persist-driver compile (XLA kernel emulation)
def test_expo_bundle_fast_path_engages_and_matches_v1():
    X, y = _expo_small()
    base = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 10, "max_bin": 63, "learning_rate": 0.2}
    events.enable("timers")
    events.reset()
    try:
        bst_p = lgb.train({**base, "tpu_persist_scan": "force"},
                          lgb.Dataset(X, y), 16, verbose_eval=False)
        counts = events.counts_snapshot()
    finally:
        events.reset()
        events.disable()
    inner = bst_p._booster.tree_learner.dataset
    assert len(inner.groups) < inner.num_features, \
        "expected EFB bundles in the Expo shape"
    assert bool(np.any(inner.needs_fix))
    # the telemetry counters prove WHICH path trained: all 16 trees on the
    # persist driver, the bundle block-scan grower built, zero v1 trees
    assert counts.get("tree_learner::persist_scan_trees", 0) >= 16, counts
    assert counts.get("tree_learner::persist_bundle_blockscan", 0) >= 1, \
        counts
    assert counts.get("tree_learner::v1_grow_trees", 0) == 0, counts

    bst_v1 = lgb.train({**base, "tpu_persist_scan": "off"},
                       lgb.Dataset(X, y), 16, verbose_eval=False)
    # early iterations match exactly; past that the f32 fix residual can
    # flip a near-tie the f64 v1 fix resolves the other way (same trade
    # the EFB persist test documents) — full models compare by quality
    p = bst_p.predict(X[:1024], num_iteration=4)
    v = bst_v1.predict(X[:1024], num_iteration=4)
    np.testing.assert_allclose(p, v, rtol=1e-4, atol=1e-6)
    acc_p = ((bst_p.predict(X) > 0.5) == y).mean()
    acc_v = ((bst_v1.predict(X) > 0.5) == y).mean()
    assert abs(acc_p - acc_v) < 0.02, (acc_p, acc_v)


@pytest.mark.slow  # tier-1 870s budget: profile --merge --run is covered in tier-1
def test_profile_cli_expo_smoke(tmp_path):
    """`python -m lightgbm_tpu.profile --shape expo` runs tier-1-safe on
    CPU (xplane off) and writes a BENCH_phases.json-style snapshot with
    the per-category attribution + path counters."""
    from lightgbm_tpu.profile import main
    out = tmp_path / "phases.json"
    try:
        rc = main(["--shape", "expo", "4096", "2", "xplane=0",
                   "num_leaves=15", "max_bin=63",
                   # keep the engine's TRACE-mode auto-export out of CWD
                   "telemetry_out=%s" % (tmp_path / "trace.json"),
                   "phases_out=%s" % out])
    finally:
        events.reset()
        events.disable()
    assert rc == 0
    snap = json.loads(out.read_text())
    assert "expo" in snap
    cats = snap["expo"]["categories"]
    assert "tree_learner" in cats or "ops" in cats, cats
    # the path counters ride the snapshot so fast-path engagement is
    # visible next to the attribution
    assert "counters" in snap["expo"]


def test_allstate_yahoo_generators_shape():
    """The two never-benched reference shapes produce what their bench
    runs assume: sparse one-hot CSR with ~4.1k columns, and 700-feature
    LTR groups that tile the row count."""
    from lightgbm_tpu.data.synth import make_allstate_like, make_yahoo_like
    X, y = make_allstate_like(n_rows=2000)
    assert X.shape[0] == 2000 and X.shape[1] > 4000
    assert hasattr(X, "tocsr")                     # stays sparse
    assert set(np.unique(np.asarray(X[:100].todense()))) >= {0.0, 1.0}
    assert y.shape == (2000,)
    Xy, yy, g = make_yahoo_like(n_rows=2400, docs_per_query=24)
    assert Xy.shape == (2400, 700)
    assert g.sum() == len(yy) == 2400
