"""Resilience subsystem: atomic checkpoint/resume, collective retry,
deterministic fault injection.

The acceptance contract (ISSUE 5):
  * a run killed at iteration k and auto-resumed produces a BYTE-IDENTICAL
    final model to the uninterrupted run (all boosting modes, with the
    host RNG streams — bagging / GOSS / DART drops / feature_fraction —
    mid-stream);
  * a corrupted latest checkpoint falls back to the previous valid one;
  * a dropped DCN collective surfaces as a bounded-retry LightGBMError
    (no hang), with collective::retry / collective::timeout pinned;
  * checkpoint::write overhead stays < 3% of train wall.

The two-process distributed kill/resume sibling lives at the bottom
(slow-marked); everything above runs single-process in tier-1.
"""
import json
import os
import shutil
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.resilience import checkpoint as ckpt
from lightgbm_tpu.resilience import faults, restore, retry
from lightgbm_tpu.resilience.faults import FaultPlan, TrainingKilled
from lightgbm_tpu.utils.log import LightGBMError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_binary(n=900, nf=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, nf))
    y = (X[:, 0] - 0.5 * X[:, 2] + rng.normal(size=n) * 0.3 > 0)
    return X, y.astype(float)


def _fresh_dir(tmp_path, name):
    d = str(tmp_path / name)
    shutil.rmtree(d, ignore_errors=True)
    os.makedirs(d)
    return d


BASE = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
        "min_data_in_leaf": 5, "learning_rate": 0.3}


def _train(params, X, y, rounds=12):
    return lgb.train(dict(params), lgb.Dataset(X, y), rounds,
                     verbose_eval=False)


# ---------------------------------------------------------------------------
# fault-plan grammar
# ---------------------------------------------------------------------------

def test_fault_plan_grammar():
    p = FaultPlan("kill@iter=12;rank=1,drop_collective@round=3;times=2,"
                  "corrupt_checkpoint@n=2")
    assert p.kill_iter == 12 and p.kill_rank == 1
    assert p.kill_point(0) is None and p.kill_point(1) == 12
    assert p.drop_round == 3 and p.drop_times == 2
    assert p.corrupt_n == 2
    # times=2: the round fails twice, then recovers
    assert p.collective_should_drop(3) and p.collective_should_drop(3)
    assert not p.collective_should_drop(3)
    assert not p.collective_should_drop(2)
    # rank-less kill applies to every rank
    assert FaultPlan("kill@iter=4").kill_point(7) == 4


def test_fault_plan_grammar_stall_resize():
    p = FaultPlan("stall@round=2;secs=3,resize@iter=9;world=4")
    assert p.stall_round == 2 and p.stall_secs == 3 and p.stall_rank is None
    assert p.collective_stall_secs(2) == 3.0
    assert p.collective_stall_secs(1) == 0.0
    assert p.resize_iter == 9 and p.resize_world == 4
    # rank-filtered stall: this process is rank 0
    q = FaultPlan("stall@round=1;secs=2;rank=5")
    assert q.collective_stall_secs(1) == 0.0
    # batch clamping sees the earliest stop point, rank filters ignored
    assert FaultPlan("kill@iter=7;rank=1,resize@iter=5;world=2"
                     ).clamp_iter() == 5
    assert FaultPlan("kill@iter=3").clamp_iter() == 3
    assert FaultPlan("stall@round=1;secs=1").clamp_iter() is None


def test_resize_raises_typed_error():
    from lightgbm_tpu.resilience.faults import TrainingResized
    from lightgbm_tpu.telemetry import flight
    flight.disarm()   # check_kill dumps wherever a previous test left
    #                   the recorder armed (default '.': repo litter)
    p = FaultPlan("resize@iter=6;world=2")
    p.check_kill(5)                      # before the resize point: fine
    with pytest.raises(TrainingResized) as exc:
        p.check_kill(6, rank=3)          # fires on EVERY rank
    assert exc.value.target_world == 2
    assert isinstance(exc.value, TrainingKilled)
    assert "world=2" in str(exc.value)
    # when both land on the same run, the earlier point wins
    pk = FaultPlan("kill@iter=4,resize@iter=8;world=2")
    with pytest.raises(TrainingKilled) as exc2:
        pk.check_kill(4)
    assert not isinstance(exc2.value, TrainingResized)


@pytest.mark.parametrize("bad", ["kill", "kill@iter=x", "explode@n=1",
                                 "drop_collective@times=1",
                                 "corrupt_checkpoint@iter=1",
                                 # duplicates would silently last-win
                                 "kill@iter=1,kill@iter=2",
                                 "drop_collective@round=1,"
                                 "drop_collective@round=5",
                                 # stall/resize mirror the same rules
                                 "stall@round=1",
                                 "stall@secs=2",
                                 "stall@round=1;secs=-1",
                                 "stall@round=1;secs=2,stall@round=3;secs=1",
                                 "resize@iter=1",
                                 "resize@world=2",
                                 "resize@iter=1;world=0",
                                 "resize@iter=1;world=2,"
                                 "resize@iter=3;world=1"])
def test_fault_plan_rejects_malformed(bad):
    with pytest.raises(LightGBMError):
        FaultPlan(bad)


# ---------------------------------------------------------------------------
# container: CRC + atomic write
# ---------------------------------------------------------------------------

def test_checkpoint_container_roundtrip_and_crc(tmp_path):
    path = str(tmp_path / "c.lgc")
    arrays = {"a": np.arange(7, dtype=np.float64),
              "txt": np.frombuffer(b"hello", dtype=np.uint8)}
    blob = ckpt.pack_checkpoint(5, arrays, {"kind": "train", "rank": 0,
                                            "config_hash": "ch",
                                            "data_fingerprint": "fp"})
    ckpt.atomic_write_bytes(path, blob)
    assert not [n for n in os.listdir(str(tmp_path)) if "tmp" in n]
    meta, back = ckpt.load_checkpoint(path)
    assert meta["iteration"] == 5 and meta["config_hash"] == "ch"
    np.testing.assert_array_equal(back["a"], arrays["a"])
    assert back["txt"].tobytes() == b"hello"
    # flip payload bytes -> CRC mismatch must be detected
    with open(path, "r+b") as f:
        f.seek(-4, os.SEEK_END)
        f.write(b"\x00\x01\x02\x03")
    with pytest.raises(ckpt.CheckpointError):
        ckpt.load_checkpoint(path)
    # truncation too
    with open(path, "rb") as f:
        head = f.read(40)
    with open(path, "wb") as f:
        f.write(head)
    with pytest.raises(ckpt.CheckpointError):
        ckpt.load_checkpoint(path)


def test_checkpoint_keep_prunes(tmp_path):
    w = ckpt.CheckpointWriter(str(tmp_path), keep=2, cfg_hash="h",
                              fingerprint="fp")
    for it in (2, 4, 6, 8):
        w.write_model_text("model %d" % it, it)
    assert [i for i, _ in ckpt.list_checkpoints(str(tmp_path))] == [6, 8]


# ---------------------------------------------------------------------------
# kill -> auto-resume -> byte-identical final model
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("boosting,extra", [
    # gbdt is the cheap tier-1 sibling (bagging + feature-fraction RNG
    # mid-stream); goss/dart/rf ride the slow tier — they share the same
    # capture/restore machinery plus their per-mode state hooks
    ("gbdt", {"bagging_fraction": 0.8, "bagging_freq": 2,
              "feature_fraction": 0.7}),
    pytest.param("goss", {}, marks=pytest.mark.slow),
    pytest.param("dart", {"drop_rate": 0.5}, marks=pytest.mark.slow),
    pytest.param("rf", {"bagging_fraction": 0.7, "bagging_freq": 1},
                 marks=pytest.mark.slow),
])
def test_kill_and_resume_byte_identical(tmp_path, boosting, extra):
    """Uninterrupted run == killed-at-k + auto-resumed run, byte for byte
    — including the mid-stream host RNG state (bagging draw, GOSS
    sampling, DART drops, feature-fraction columns)."""
    X, y = _make_binary()
    d = _fresh_dir(tmp_path, "ck")
    params = dict(BASE, boosting=boosting, snapshot_freq=4,
                  checkpoint_dir=d, **extra)
    model_a = _train(params, X, y).model_to_string(num_iteration=-1)
    # wipe and replay the same run, preempted before iteration 10
    shutil.rmtree(d)
    os.makedirs(d)
    killed = dict(params, tpu_fault_plan="kill@iter=10")
    with pytest.raises(TrainingKilled):
        _train(killed, X, y)
    iters = [i for i, _ in ckpt.list_checkpoints(d)]
    assert iters == [4, 8]
    resumed = _train(params, X, y)
    assert resumed.num_trees() == 12
    assert resumed.model_to_string(num_iteration=-1) == model_a


def test_corrupt_latest_falls_back_to_previous(tmp_path):
    """corrupt_checkpoint@n=2 poisons the iteration-8 snapshot; resume
    must reject it on CRC, fall back to iteration 4, and STILL finish
    byte-identical to the uninterrupted run."""
    X, y = _make_binary()
    d = _fresh_dir(tmp_path, "ck")
    # same params as the gbdt kill/resume case: the three trains here
    # reuse its compiled programs instead of building a fresh set
    params = dict(BASE, snapshot_freq=4, checkpoint_dir=d,
                  bagging_fraction=0.8, bagging_freq=2,
                  feature_fraction=0.7)
    model_a = _train(params, X, y).model_to_string(num_iteration=-1)
    shutil.rmtree(d)
    os.makedirs(d)
    killed = dict(params,
                  tpu_fault_plan="kill@iter=10,corrupt_checkpoint@n=2")
    with pytest.raises(TrainingKilled):
        _train(killed, X, y)
    cfg = lgb.Config(params)
    ds = lgb.Dataset(X, y)
    ds.construct()
    found = restore.find_restorable(cfg, ds._inner)
    assert found is not None and int(found[0]["iteration"]) == 4
    resumed = _train(params, X, y)
    assert resumed.model_to_string(num_iteration=-1) == model_a


def test_foreign_config_or_data_starts_fresh(tmp_path):
    """A checkpoint_dir holding a DIFFERENT run's snapshots (config hash
    or dataset fingerprint mismatch) must not be resumed from — while the
    volatile keys (num_iterations, fault plan, telemetry) keep matching."""
    X, y = _make_binary()
    d = _fresh_dir(tmp_path, "ck")
    params = dict(BASE, snapshot_freq=4, checkpoint_dir=d)
    _train(params, X, y, rounds=8)
    assert ckpt.list_checkpoints(d)
    ds = lgb.Dataset(X, y)
    ds.construct()
    # matching run resumes ...
    assert restore.find_restorable(lgb.Config(params), ds._inner) is not None
    # ... and so does one differing only in volatile keys
    volatile = dict(params, num_iterations=50, tpu_fault_plan="kill@iter=9",
                    tpu_telemetry="timers")
    assert restore.find_restorable(lgb.Config(volatile),
                                   ds._inner) is not None
    # different config (num_leaves): config-hash mismatch -> fresh
    other = dict(params, num_leaves=15)
    assert restore.find_restorable(lgb.Config(other), ds._inner) is None
    # different data, same config: fingerprint mismatch -> fresh
    X2, y2 = _make_binary(seed=9)
    ds2 = lgb.Dataset(X2, y2)
    ds2.construct()
    assert restore.find_restorable(lgb.Config(params), ds2._inner) is None


def test_checkpoint_params_roundtrip_and_alias(tmp_path):
    """snapshot_freq rides its reference alias (save_period) and the new
    checkpoint params round-trip into the model's parameters block, like
    the predict_device params do."""
    cfg = lgb.Config({"save_period": 7})
    assert cfg.snapshot_freq == 7
    X, y = _make_binary(n=400)
    d = _fresh_dir(tmp_path, "ck")
    params = dict(BASE, save_period=4, checkpoint_dir=d, checkpoint_keep=1)
    b = _train(params, X, y, rounds=8)
    assert len(ckpt.list_checkpoints(d)) == 1   # keep=1 pruned
    text = b.model_to_string(num_iteration=-1)
    saved = json.loads(text.split("parameters:\n", 1)[1]
                       .split("\nend of parameters", 1)[0])
    assert saved["checkpoint_dir"] == d
    assert saved["checkpoint_keep"] == 1
    assert saved["snapshot_freq"] == 4


@pytest.mark.slow
def test_kill_resume_with_early_stopping_state(tmp_path):
    """The early-stopping best trackers ride the checkpoint: a resumed
    run keeps the same patience clock and rollback point, so it stops at
    the same iteration with the same best_iteration and a byte-identical
    saved model."""
    rng = np.random.default_rng(7)
    X = rng.normal(size=(600, 5))
    y = (X[:, 0] + rng.normal(size=600) * 1.5 > 0).astype(float)
    Xv = rng.normal(size=(250, 5))
    yv = (Xv[:, 0] + rng.normal(size=250) * 1.5 > 0).astype(float)
    d = _fresh_dir(tmp_path, "ck")
    params = dict(BASE, metric="binary_logloss", snapshot_freq=4,
                  checkpoint_dir=d)

    def run(extra=None):
        p = dict(params, **(extra or {}))
        return lgb.train(p, lgb.Dataset(X, y, params=p), 40,
                         valid_sets=[lgb.Dataset(Xv, yv)],
                         early_stopping_rounds=4, verbose_eval=False)

    b_a = run()
    # the run must stop early AFTER the kill point for the test to bite
    assert 4 < b_a.best_iteration < 40
    model_a = b_a.model_to_string()
    shutil.rmtree(d)
    os.makedirs(d)
    with pytest.raises(TrainingKilled):
        run({"tpu_fault_plan": "kill@iter=4"})
    b_r = run()
    assert b_r.best_iteration == b_a.best_iteration
    assert b_r.model_to_string() == model_a


@pytest.mark.slow
def test_resume_of_init_model_run_trains_full_target(tmp_path):
    """A checkpointed run started from an init model: num_boost_round
    counts NEW rounds beyond the graft, and a resume must finish exactly
    that target (not stop short at the absolute checkpoint iteration)."""
    X, y = _make_binary()
    b_init = lgb.train(dict(BASE), lgb.Dataset(X, y), 5,
                       verbose_eval=False)
    d = _fresh_dir(tmp_path, "ck")
    params = dict(BASE, snapshot_freq=4, checkpoint_dir=d)
    model_a = lgb.train(dict(params), lgb.Dataset(X, y), 10,
                        init_model=b_init,
                        verbose_eval=False).model_to_string(
        num_iteration=-1)
    shutil.rmtree(d)
    os.makedirs(d)
    killed = dict(params, tpu_fault_plan="kill@iter=12")
    with pytest.raises(TrainingKilled):
        lgb.train(killed, lgb.Dataset(X, y), 10, init_model=b_init,
                  verbose_eval=False)
    resumed = lgb.train(dict(params), lgb.Dataset(X, y), 10,
                        init_model=b_init, verbose_eval=False)
    assert resumed.num_trees() == 15          # 5 grafted + 10 new
    assert resumed.model_to_string(num_iteration=-1) == model_a


# ---------------------------------------------------------------------------
# telemetry counters (pinned like predict::serve_compile)
# ---------------------------------------------------------------------------

def test_checkpoint_counters_pinned(tmp_path):
    """checkpoint::write/bytes/restore pinned the same way
    predict::serve_compile is — and re-running a finished job is a pure
    restore: zero extra writes, byte-identical model out."""
    from lightgbm_tpu import telemetry
    X, y = _make_binary()
    d = _fresh_dir(tmp_path, "ck")
    params = dict(BASE, snapshot_freq=4, checkpoint_dir=d)
    telemetry.enable("timers")
    try:
        telemetry.reset()
        model_a = _train(params, X, y).model_to_string(
            num_iteration=-1)                      # writes at 4, 8, 12
        counts = telemetry.events.counts_snapshot()
        assert counts.get("checkpoint::write", 0) == 3, counts
        assert counts.get("checkpoint::bytes", 0) > 0, counts
        assert counts.get("checkpoint::restore", 0) == 0, counts
        scopes = telemetry.events.snapshot_full()
        assert "checkpoint::write" in scopes
        telemetry.reset()
        again = _train(params, X, y)               # resumes at 12: no-op
        counts = telemetry.events.counts_snapshot()
        assert counts.get("checkpoint::restore", 0) == 1, counts
        assert counts.get("checkpoint::write", 0) == 0, counts
        assert again.num_trees() == 12
        assert again.model_to_string(num_iteration=-1) == model_a
    finally:
        telemetry.reset()
        telemetry.disable()


def test_checkpoint_write_overhead_under_3_percent(tmp_path):
    """The acceptance budget: checkpoint::write seconds < 3% of train
    wall on a HIGGS-like shape (bench.py's checkpoint phase measures the
    same ratio at full scale)."""
    from lightgbm_tpu import telemetry
    from lightgbm_tpu.data.synth import make_higgs_like
    X, y = make_higgs_like(6_000)
    # tmpfs when available: this CI box's fsync latency is wildly
    # variable (0.1-1s under IO contention) and would dominate the toy
    # 10s train wall; the pin targets the serialization/write PATH cost
    # (bench.py's checkpoint phase measures real-disk overhead at the
    # 2M-row scale where the 3% budget is meant to hold)
    base = "/dev/shm" if os.access("/dev/shm", os.W_OK) else str(tmp_path)
    d = os.path.join(base, "lgbtpu_ck_overhead")
    shutil.rmtree(d, ignore_errors=True)
    os.makedirs(d)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "snapshot_freq": 8, "checkpoint_dir": d}
    telemetry.enable("timers")
    try:
        telemetry.reset()
        t0 = time.time()
        lgb.train(dict(params), lgb.Dataset(X, y), 16, verbose_eval=False)
        wall = time.time() - t0
        scopes = telemetry.events.snapshot_full()
        write_s, nwrites, _ = scopes.get("checkpoint::write",
                                         (0.0, 0, ""))
        assert nwrites == 2
        assert write_s < 0.03 * wall, \
            "checkpoint::write %.3fs of %.3fs wall" % (write_s, wall)
    finally:
        telemetry.reset()
        telemetry.disable()
        shutil.rmtree(d, ignore_errors=True)


# ---------------------------------------------------------------------------
# collective retry: bounded error instead of a hang
# ---------------------------------------------------------------------------

def test_drop_collective_bounded_retry_error():
    from lightgbm_tpu import telemetry
    telemetry.enable("timers")
    try:
        telemetry.reset()
        retry.reset_rounds()
        faults._PLAN = FaultPlan("drop_collective@round=2")
        # timeout_s=0: injected drops never reach the collective, so
        # the watchdog thread is noise here (and thread creation deep
        # into a long tier-1 run is the one flaky dependency)
        retry._POLICY = retry.RetryPolicy(timeout_s=0.0, retries=2,
                                          backoff_s=0.0)
        assert retry.guard("c1", lambda: "ok") == "ok"   # round 1 clean
        with pytest.raises(LightGBMError) as exc:        # round 2 dropped
            retry.guard("c2", lambda: "never")
        assert "after 3 attempt(s)" in str(exc.value)
        counts = telemetry.events.counts_snapshot()
        assert counts.get("collective::retry", 0) == 2, counts
        assert counts.get("faults::injected", 0) == 3, counts
    finally:
        faults.reset()
        retry._POLICY = retry.RetryPolicy()
        telemetry.reset()
        telemetry.disable()


def test_drop_collective_transient_recovers():
    retry.reset_rounds()
    faults._PLAN = FaultPlan("drop_collective@round=1;times=1")
    retry._POLICY = retry.RetryPolicy(timeout_s=0.0, retries=2,
                                      backoff_s=0.0)
    try:
        assert retry.guard("c", lambda: 42) == 42   # fails once, retried
    finally:
        faults.reset()
        retry._POLICY = retry.RetryPolicy()


def test_collective_timeout_no_hang():
    """A peer that never answers: the guard's deadline converts the hang
    into a clean LightGBMError in bounded time."""
    from lightgbm_tpu import telemetry
    telemetry.enable("timers")
    try:
        telemetry.reset()
        retry.reset_rounds()
        retry._POLICY = retry.RetryPolicy(timeout_s=0.2, retries=1,
                                          backoff_s=0.0)
        t0 = time.time()
        with pytest.raises(LightGBMError):
            retry.guard("stuck", time.sleep, 60)
        assert time.time() - t0 < 5.0
        counts = telemetry.events.counts_snapshot()
        assert counts.get("collective::timeout", 0) == 2, counts
    finally:
        retry._POLICY = retry.RetryPolicy()
        telemetry.reset()
        telemetry.disable()


def test_retry_policy_from_config():
    cfg = lgb.Config({"tpu_collective_timeout": 7.5,
                      "tpu_collective_retries": 4,
                      "tpu_collective_backoff": 0.0})
    retry.configure_from_config(cfg)
    try:
        pol = retry.policy()
        assert (pol.timeout_s, pol.retries, pol.backoff_s) == (7.5, 4, 0.0)
        # soft deadline: auto = a quarter of the hard deadline
        assert pol.effective_soft_s() == pytest.approx(7.5 / 4)
    finally:
        retry._POLICY = retry.RetryPolicy()
    cfg2 = lgb.Config({"tpu_collective_timeout": 10.0,
                       "tpu_collective_soft_timeout": 2.0})
    retry.configure_from_config(cfg2)
    try:
        assert retry.policy().effective_soft_s() == 2.0
    finally:
        retry._POLICY = retry.RetryPolicy()
    # a soft deadline >= the hard one (or timeout 0) disables the watchdog
    assert retry.RetryPolicy(timeout_s=1.0,
                             soft_timeout_s=5.0).effective_soft_s() == 0.0
    assert retry.RetryPolicy(timeout_s=0.0).effective_soft_s() == 0.0


# ---------------------------------------------------------------------------
# straggler watchdog: collective::stall + flight dump BEFORE the hard
# deadline decides (the ISSUE-12 acceptance pin)
# ---------------------------------------------------------------------------

def test_stall_fault_emits_stall_event_and_flight_dump(tmp_path):
    """A stall@ fault longer than the soft deadline but shorter than the
    hard one: the collective SUCCEEDS, yet collective::stall is counted
    and a flight record is on disk from before the call returned."""
    from lightgbm_tpu import telemetry
    from lightgbm_tpu.telemetry import flight
    d = _fresh_dir(tmp_path, "stall")
    telemetry.enable("timers")
    try:
        telemetry.reset()
        flight.reset()
        flight.arm(dump_dir=d)
        retry.reset_rounds()
        faults._PLAN = FaultPlan("stall@round=1;secs=1")
        retry._POLICY = retry.RetryPolicy(timeout_s=30.0, retries=0,
                                          backoff_s=0.0,
                                          soft_timeout_s=0.1)
        assert retry.guard("allgather:probe", lambda: "ok") == "ok"
        counts = telemetry.events.counts_snapshot()
        assert counts.get("collective::stall", 0) == 1, counts
        assert counts.get("collective::timeout", 0) == 0, counts
        assert counts.get("faults::injected", 0) == 1, counts
        dump = flight.last_dump_path()
        assert dump and os.path.exists(dump)
        rec = json.load(open(dump))
        assert rec["reason"].startswith("collective_stall:")
        stalls = [e for e in rec["events"]
                  if e["kind"] == "collective_stall"]
        assert stalls and stalls[0]["soft_deadline_s"] == 0.1
    finally:
        faults.reset()
        retry._POLICY = retry.RetryPolicy()
        flight.disarm()
        telemetry.reset()
        telemetry.disable()


def test_stall_past_hard_deadline_still_bounded(tmp_path):
    """A stall longer than the hard deadline: the soft watchdog fires
    first (stall counted), then the deadline converts the straggler into
    the usual bounded timeout error — never a hang."""
    from lightgbm_tpu import telemetry
    from lightgbm_tpu.telemetry import flight
    telemetry.enable("timers")
    flight.disarm()       # the stall path dumps wherever a previous
    try:                  # test left the recorder armed
        telemetry.reset()
        retry.reset_rounds()
        faults._PLAN = FaultPlan("stall@round=1;secs=30")
        retry._POLICY = retry.RetryPolicy(timeout_s=0.4, retries=0,
                                          backoff_s=0.0,
                                          soft_timeout_s=0.1)
        t0 = time.time()
        with pytest.raises(LightGBMError):
            retry.guard("allgather:wedge", lambda: "never")
        assert time.time() - t0 < 5.0
        counts = telemetry.events.counts_snapshot()
        assert counts.get("collective::stall", 0) == 1, counts
        assert counts.get("collective::timeout", 0) == 1, counts
    finally:
        faults.reset()
        retry._POLICY = retry.RetryPolicy()
        telemetry.reset()
        telemetry.disable()


def test_peer_loss_error_names_resume_point():
    """After a checkpoint write, a permanently-gone peer surfaces as
    'resumable at iteration K on a smaller mesh', not a generic failure
    (the watchdog half of the elastic story)."""
    retry.reset_rounds()
    retry._POLICY = retry.RetryPolicy(timeout_s=0.0, retries=0,
                                      backoff_s=0.0)
    try:
        retry.set_resume_hint(24, 4)

        def gone():
            raise ConnectionError("peer vanished")
        with pytest.raises(LightGBMError) as exc:
            retry.guard("allgather:x", gone)
        assert "resumable at iteration 24 on a smaller mesh" in \
            str(exc.value)
        assert "num_machines < 4" in str(exc.value)
        # single-host hint names the checkpoint, not a mesh
        retry.reset_rounds()
        retry.set_resume_hint(8, 1)
        with pytest.raises(LightGBMError) as exc2:
            retry.guard("allgather:y", gone)
        assert "resumable at iteration 8 from checkpoint_dir" in \
            str(exc2.value)
    finally:
        retry.set_resume_hint(None)
        retry._POLICY = retry.RetryPolicy()


# ---------------------------------------------------------------------------
# checkpoint hygiene: orphaned tmp sweep + concurrent-prune tolerance
# ---------------------------------------------------------------------------

def test_writer_sweeps_orphaned_tmp_files(tmp_path):
    """A kill mid-write leaves `.ckpt_*.tmp` behind forever; the next
    saver startup sweeps them: own-rank orphans unconditionally, foreign
    ones (another rank's snapshot, the shared manifest) only once old
    enough to be provably dead — a shared dir may have live writers."""
    d = _fresh_dir(tmp_path, "tmpsweep")
    own_orphan = os.path.join(d, ".ckpt_00000004.r0.lgc.1234.tmp")
    aged_foreign = os.path.join(d, ".elastic.manifest.json.77.tmp")
    live_foreign = os.path.join(d, ".ckpt_00000002.r7.lgc.99.tmp")
    for p in (own_orphan, aged_foreign, live_foreign):
        with open(p, "w") as f:
            f.write("torn half-write")
    os.utime(aged_foreign, (time.time() - 3600, time.time() - 3600))
    keepers = [os.path.join(d, "keep.txt"),
               os.path.join(d, "tmpnotdot.tmp.txt")]
    for k in keepers:
        with open(k, "w") as f:
            f.write("x")
    ckpt.CheckpointWriter(d, keep=2, cfg_hash="h", fingerprint="fp")
    assert not os.path.exists(own_orphan)      # rank 0's own: swept
    assert not os.path.exists(aged_foreign)    # provably dead: swept
    assert os.path.exists(live_foreign)        # maybe mid-write: kept
    assert all(os.path.exists(k) for k in keepers)


def test_prune_tolerates_concurrent_delete(tmp_path, monkeypatch):
    """checkpoint_keep pruning on a shared directory: a concurrent rank
    removing the same stale snapshot must not crash the writer."""
    d = _fresh_dir(tmp_path, "prunerace")
    w = ckpt.CheckpointWriter(d, keep=1, cfg_hash="h", fingerprint="fp")
    w.write_model_text("m2", 2)
    real_remove = os.remove
    raced = {"n": 0}

    def racing_remove(path):
        # the other rank wins the unlink race on every prune target
        if path.endswith(".lgc"):
            raced["n"] += 1
            real_remove(path)
            raise FileNotFoundError(path)
        real_remove(path)

    monkeypatch.setattr(os, "remove", racing_remove)
    w.write_model_text("m4", 4)          # prunes ckpt_2 under the race
    monkeypatch.setattr(os, "remove", real_remove)
    assert raced["n"] >= 1
    assert [i for i, _ in ckpt.list_checkpoints(d)] == [4]


# ---------------------------------------------------------------------------
# engine resume edge (satellite): early-stopped init model
# ---------------------------------------------------------------------------

def test_init_model_resumes_from_rollback_point():
    """keep_training_booster + early stopping leaves the booster holding
    trees past best_iteration; continuing from it as init_model must
    restore the ROLLBACK point (best_iteration), not graft the dead tail
    — byte-equal to resuming from an explicitly truncated model file."""
    rng = np.random.default_rng(3)
    X = rng.normal(size=(400, 5))
    y = (X[:, 0] + rng.normal(size=400) * 2.0 > 0).astype(float)
    Xv = rng.normal(size=(200, 5))
    yv = (Xv[:, 0] + rng.normal(size=200) * 2.0 > 0).astype(float)
    params = dict(BASE, metric="binary_logloss")
    ds = lgb.Dataset(X, y, params=params)
    b1 = lgb.train(dict(params), ds, 30,
                   valid_sets=[lgb.Dataset(Xv, yv)],
                   early_stopping_rounds=2, verbose_eval=False,
                   keep_training_booster=True)
    assert 0 < b1.best_iteration < 30
    assert b1.num_trees() > b1.best_iteration   # the over-trained tail
    b2 = lgb.train(dict(params), lgb.Dataset(X, y, params=params), 5,
                   init_model=b1, verbose_eval=False)
    assert b2.num_trees() == b1.best_iteration + 5
    truncated = b1.model_to_string(num_iteration=b1.best_iteration)
    b3 = lgb.train(dict(params), lgb.Dataset(X, y, params=params), 5,
                   init_model=lgb.Booster(model_str=truncated),
                   verbose_eval=False)
    assert (b2.model_to_string(num_iteration=-1)
            == b3.model_to_string(num_iteration=-1))


# ---------------------------------------------------------------------------
# two-process distributed kill/resume (slow sibling)
# ---------------------------------------------------------------------------

DIST_WORKER = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, %(repo)r)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:   # jax 0.4.x: the XLA_FLAGS above covers it
    pass
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except AttributeError:
    pass

rank = int(sys.argv[1])
port = sys.argv[2]
out = sys.argv[3]
ckdir = sys.argv[4]
refdir = sys.argv[5]
os.environ["JAX_PROCESS_ID"] = str(rank)

import lightgbm_tpu as lgb
from lightgbm_tpu.resilience import retry
from lightgbm_tpu.resilience.faults import TrainingKilled
from lightgbm_tpu.utils.log import LightGBMError

rng = np.random.default_rng(17)
n, nf = 2400, 6
X = rng.normal(size=(n, nf))
y = (X[:, 1] + 0.5 * X[:, 4] + rng.normal(size=n) * 0.3 > 0).astype(float)

params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "num_machines": 2,
          "machines": "127.0.0.1:%%s,127.0.0.1:0" %% port,
          "min_data_in_leaf": 5, "tree_learner": "data",
          "bagging_fraction": 0.8, "bagging_freq": 2,
          "feature_fraction": 0.7,
          "snapshot_freq": 3, "tpu_collective_backoff": 0.0}

def digest(b):
    return [round(float(v), 10) for v in b.predict(X[:300], raw_score=True)]

# (a) uninterrupted 9-round reference, its own snapshot stream
pa = dict(params, checkpoint_dir=refdir)
ref = digest(lgb.train(pa, lgb.Dataset(X, y), 9, verbose_eval=False))

# (b) same run, killed before iteration 6 (both ranks)
pb = dict(params, checkpoint_dir=ckdir, tpu_fault_plan="kill@iter=6")
killed = False
try:
    lgb.train(pb, lgb.Dataset(X, y), 9, verbose_eval=False)
except TrainingKilled:
    killed = True

# (c) auto-resume from the agreed per-rank snapshots -> must match (a)
pc = dict(params, checkpoint_dir=ckdir)
res = digest(lgb.train(pc, lgb.Dataset(X, y), 9, verbose_eval=False))

# (d) drop_collective: the first guarded DCN collective fails on every
# attempt on BOTH ranks -> bounded-retry LightGBMError, no hang
retry.reset_rounds()
pd = dict(params)
pd.pop("snapshot_freq")
pd["tpu_fault_plan"] = "drop_collective@round=1"
err = ""
try:
    lgb.train(pd, lgb.Dataset(X, y), 3, verbose_eval=False)
except LightGBMError as e:
    err = str(e)

with open(out, "w") as fh:
    json.dump({"rank": rank, "killed": killed, "ref": ref, "res": res,
               "match": ref == res, "err": err}, fh)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.mark.slow
def test_two_process_distributed_kill_resume(tmp_path):
    """Two jax.distributed processes: checkpointed run killed at iteration
    6, auto-resumed bit-exactly against the uninterrupted reference; plus
    a dropped DCN collective surfacing as a bounded LightGBMError on both
    ranks (no hang)."""
    port = _free_port()
    script = tmp_path / "dist_worker.py"
    script.write_text(DIST_WORKER % {"repo": REPO})
    ckdir = _fresh_dir(tmp_path, "dist_ck")
    refdir = _fresh_dir(tmp_path, "dist_ref")
    outs = [str(tmp_path / ("dr%d.json" % r)) for r in range(2)]
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(script), str(r), str(port), outs[r],
             ckdir, refdir],
            env=env, cwd=str(tmp_path),   # fault-plan flight dumps
            # with no checkpoint_dir land in the worker's cwd — keep
            # that litter in tmp, not the repo root
            stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    for p in procs:
        try:
            _, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed resilience worker timed out")
        assert p.returncode == 0, err.decode()[-2000:]
    r0 = json.load(open(outs[0]))
    r1 = json.load(open(outs[1]))
    assert r0["killed"] and r1["killed"]
    assert r0["match"] and r1["match"], (r0, r1)
    assert r0["res"] == r1["res"]            # ranks agree on the model
    for r in (r0, r1):
        assert "failed after" in r["err"], r["err"]
    # per-rank snapshot streams: both ranks wrote rank-tagged files,
    # plus the (rank-less) mesh manifest the elastic resume path reads
    ranks = {n.split(".r")[1] for n in os.listdir(ckdir)
             if n.endswith(".lgc")}
    assert ranks == {"0.lgc", "1.lgc"}
    assert os.path.exists(os.path.join(ckdir, "elastic.manifest.json"))
