"""Level-parallel persist growth: parity, admission semantics, launch count.

The PR 7 grower refactor runs an ENTIRE tree level as one compiled
program (batched multi-leaf partition + batched split-find, driven by a
bounded loop over depths) whenever `can_level_grow` holds, with leaf-wise
semantics preserved by gain-ordered admission plus an in-program no-bind
certificate that hands the tree to the historical per-split tail the
moment best-first admission could be budget-truncated. These tests pin:

  * raw-score parity: `tpu_level_grow=auto` vs `off` is BIT-EXACT on the
    persist driver (gbdt + goss, bundled Expo-like and unbundled
    HIGGS-like shapes) — the level batch is a scheduling change, not a
    numerics change;
  * the frontier edge cases — leaves dropping out at min_data_in_leaf,
    and `num_leaves` budgets under which best-first admission could be
    truncated, which the certificate refuses to the per-split tail —
    keep that parity;
  * the launch-count regression the Expo gap was about: on a level-wide
    budget (num_leaves >= 2^max_depth) a tree costs <= max_depth level
    programs and ZERO per-split fallback launches, counter-pinned via
    tree_learner::level_programs / level_fallback_splits;
  * DART and RF ride the persist driver too (PR 17: per-tree weight
    vectors traced into the fused iteration program) — device vs host
    paths are BIT-EXACT, pinned on bundled and unbundled shapes along
    with the iter-launch counter the fusion exists to shrink.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.data.synth import make_expo_like, make_higgs_like
from lightgbm_tpu.telemetry import events


def _train_counted(params, X, y, rounds=16):
    events.enable("timers")
    events.reset()
    try:
        bst = lgb.train(params, lgb.Dataset(X, y), rounds,
                        verbose_eval=False)
        counts = events.counts_snapshot()
    finally:
        events.reset()
        events.disable()
    return bst, counts


def _raw(bst, X):
    return bst.predict(X[:1500], raw_score=True)


def _higgs_small(n=5000):
    X, y = make_higgs_like(n_rows=n, seed=11)
    return X, y


def _expo_small(n=4096):
    X, y = make_expo_like(n_rows=n, seed=3)
    return X, y


# ---------------------------------------------------------------------------
# static gate
# ---------------------------------------------------------------------------

def test_can_level_grow_gate():
    from collections import namedtuple
    from lightgbm_tpu.ops.grow_persist import (LEVEL_MAX_DEPTH,
                                               can_level_grow)
    GC = namedtuple("GC", "max_depth num_leaves parallel_mode n_forced")
    ok = GC(6, 64, "data", 0)
    assert can_level_grow(ok)
    assert not can_level_grow(ok._replace(max_depth=0))      # unbounded
    assert not can_level_grow(ok._replace(max_depth=-1))
    assert not can_level_grow(ok._replace(
        max_depth=LEVEL_MAX_DEPTH + 1))                      # slot blowup
    assert can_level_grow(ok._replace(max_depth=LEVEL_MAX_DEPTH))
    assert not can_level_grow(ok._replace(num_leaves=3))     # trivial trees
    assert not can_level_grow(ok._replace(parallel_mode="voting"))
    assert not can_level_grow(ok._replace(n_forced=2))       # ordered splits


# ---------------------------------------------------------------------------
# raw-score parity: level program vs per-split persist path
# ---------------------------------------------------------------------------

@pytest.mark.slow  # persist-driver compile x2 (XLA kernel emulation)
@pytest.mark.parametrize("objective_extra", [
    {},                                                       # gbdt
    {"boosting": "goss", "top_rate": 0.3, "other_rate": 0.15},
], ids=["gbdt", "goss"])
def test_level_parity_higgs_unbundled(objective_extra):
    X, y = _higgs_small()
    base = {"objective": "binary", "num_leaves": 16, "max_depth": 4,
            "verbosity": -1, "min_data_in_leaf": 10, "max_bin": 63,
            "learning_rate": 0.2, "tpu_persist_scan": "force",
            **objective_extra}
    bst_lvl, c_lvl = _train_counted(base, X, y)
    bst_off, c_off = _train_counted({**base, "tpu_level_grow": "off"},
                                    X, y)
    assert c_lvl.get("tree_learner::persist_scan_trees", 0) >= 16, c_lvl
    assert c_lvl.get("tree_learner::level_programs", 0) >= 16, c_lvl
    assert c_off.get("tree_learner::level_programs", 0) == 0, c_off
    np.testing.assert_array_equal(_raw(bst_lvl, X), _raw(bst_off, X))


@pytest.mark.slow
def test_level_parity_expo_bundled():
    X, y = _expo_small()
    base = {"objective": "binary", "num_leaves": 32, "max_depth": 5,
            "verbosity": -1, "min_data_in_leaf": 10, "max_bin": 63,
            "learning_rate": 0.2, "tpu_persist_scan": "force"}
    bst_lvl, c_lvl = _train_counted(base, X, y)
    bst_off, c_off = _train_counted({**base, "tpu_level_grow": "off"},
                                    X, y)
    inner = bst_lvl._booster.tree_learner.dataset
    assert len(inner.groups) < inner.num_features, \
        "expected EFB bundles in the Expo shape"
    assert c_lvl.get("tree_learner::level_programs", 0) >= 16, c_lvl
    assert c_off.get("tree_learner::level_fallback_splits", 0) >= 16, c_off
    np.testing.assert_array_equal(_raw(bst_lvl, X), _raw(bst_off, X))


# ---------------------------------------------------------------------------
# frontier-mask edge cases
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_level_frontier_min_data_dropout():
    """min_data_in_leaf large enough that frontier leaves stop splitting
    mid-tree: the frontier mask shrinks level over level and the parity
    with best-first growth must survive the dropouts."""
    X, y = _higgs_small()
    base = {"objective": "binary", "num_leaves": 32, "max_depth": 5,
            "verbosity": -1, "min_data_in_leaf": len(y) // 12,
            "max_bin": 63, "learning_rate": 0.2,
            "tpu_persist_scan": "force"}
    bst_lvl, c_lvl = _train_counted(base, X, y)
    bst_off, _ = _train_counted({**base, "tpu_level_grow": "off"}, X, y)
    assert c_lvl.get("tree_learner::level_programs", 0) > 0, c_lvl
    np.testing.assert_array_equal(_raw(bst_lvl, X), _raw(bst_off, X))


@pytest.mark.slow
def test_level_admission_budget_truncation_refuses():
    """num_leaves strictly between 2^(md-1) and 2^md: best-first
    admission COULD be budget-truncated mid-level, so the no-bind
    certificate must refuse at the root (remaining budget 11 < the
    positive-gain frontier's completion capacity 2^4-1 = 15) and hand
    the whole tree to the per-split tail — zero level programs, every
    split counted as a fallback, and the scores still match best-first
    exactly. (A mid-tree handoff the other way is impossible by design:
    the certificate margin (budget - capacity) is non-decreasing level
    over level, so once it holds at the root it holds to the leaves.)"""
    X, y = _higgs_small()
    base = {"objective": "binary", "num_leaves": 12, "max_depth": 4,
            "verbosity": -1, "min_data_in_leaf": 10, "max_bin": 63,
            "learning_rate": 0.2, "tpu_persist_scan": "force"}
    bst_lvl, c_lvl = _train_counted(base, X, y)
    bst_off, _ = _train_counted({**base, "tpu_level_grow": "off"}, X, y)
    assert c_lvl.get("tree_learner::level_programs", 0) == 0, c_lvl
    assert c_lvl.get("tree_learner::level_fallback_splits", 0) > 0, c_lvl
    np.testing.assert_array_equal(_raw(bst_lvl, X), _raw(bst_off, X))


# ---------------------------------------------------------------------------
# launch-count regression (the Expo gap)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_expo_level_launches_per_tree_bounded():
    """On a level-wide budget (num_leaves >= 2^max_depth) an Expo-shaped
    tree must cost <= max_depth level programs and ZERO per-split
    fallback launches — the ~num_leaves-1 small-kernel launches per tree
    that made Expo 0.23x the anchor are gone."""
    X, y = _expo_small()
    rounds, md = 16, 6
    base = {"objective": "binary", "num_leaves": 1 << md, "max_depth": md,
            "verbosity": -1, "min_data_in_leaf": 10, "max_bin": 63,
            "learning_rate": 0.2, "tpu_persist_scan": "force"}
    bst, c = _train_counted(base, X, y, rounds=rounds)
    assert bst.num_trees() == rounds
    lv = c.get("tree_learner::level_programs", 0)
    fb = c.get("tree_learner::level_fallback_splits", 0)
    assert 0 < lv <= rounds * md, c
    assert fb == 0, c
    # per-split growth of the same trees would launch one split_pass per
    # split; the level path replaces them all with <= md programs/tree
    n_splits = sum(
        bst._booster.models[t].num_leaves - 1 for t in range(rounds))
    assert lv < n_splits, (lv, n_splits)
    # the whole-iteration fusion (PR 17): 16 gbdt iterations batch into
    # ceil(16/16) = 1 driver invocation — the iter-launch counter must
    # show the amortization, not one launch per tree
    il = c.get("tree_learner::iter_launches", 0)
    assert 0 < il <= (rounds + 15) // 16 + 1, c
    assert il < rounds, c


# ---------------------------------------------------------------------------
# Mosaic level kernels (interpreter) vs the XLA emulation
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_level_mosaic_kernels_interpret_match_emulation(monkeypatch):
    """The production TPU level path (make_level_pass multi-leaf
    partition + in-pass histograms) in Pallas INTERPRETER mode must
    reproduce the XLA-emulation trees. Skips on jax < 0.5, whose
    interpret mode cannot discharge the dynamic-grid kernels
    (make_persist_grower falls back to the emulation loudly there, so
    interpret-vs-emulation would assert nothing)."""
    from lightgbm_tpu.ops.pallas_compat import dynamic_grid_interpret_ok
    from lightgbm_tpu.treelearner.serial import SerialTreeLearner
    if not dynamic_grid_interpret_ok():
        pytest.skip("pallas interpret mode cannot discharge the "
                    "dynamic-grid level kernels on this jax (< 0.5)")
    X, y = _higgs_small(2048)
    base = {"objective": "binary", "num_leaves": 16, "max_depth": 4,
            "verbosity": -1, "min_data_in_leaf": 10, "max_bin": 31,
            "learning_rate": 0.2, "tpu_persist_scan": "force"}
    bst_emu, _ = _train_counted(base, X, y)
    monkeypatch.setattr(SerialTreeLearner, "_persist_kernel_mode",
                        staticmethod(lambda: ("pallas", True)))
    bst_mos, c_mos = _train_counted(base, X, y)
    assert c_mos.get("tree_learner::level_programs", 0) > 0, c_mos
    np.testing.assert_allclose(_raw(bst_mos, X), _raw(bst_emu, X),
                               rtol=1e-4, atol=1e-6)


# ---------------------------------------------------------------------------
# DART / RF on the fused persist driver (PR 17)
# ---------------------------------------------------------------------------

def _trees_only(bst):
    """Model string minus the parameters block (the two runs differ in
    tpu_persist_scan by construction; the TREES must not)."""
    return bst.model_to_string().split("\nparameters:")[0]


@pytest.mark.slow
@pytest.mark.parametrize("shape", ["higgs_unbundled", "expo_bundled"])
@pytest.mark.parametrize("extra", [
    {"boosting": "dart", "drop_rate": 0.3},
    {"boosting": "rf", "bagging_freq": 1, "bagging_fraction": 0.7},
], ids=["dart", "rf"])
def test_dart_rf_device_host_parity(extra, shape):
    """Pre-PR-17 these modes pinned the persist driver INERT
    (supports_batch=False). Now DART's drop/normalize deltas and RF's
    bagged-average iterations run inside the fused iteration program —
    per-tree weight vectors computed host-side, applied as traced
    vectors — and the device path must match the host path BIT-EXACTLY:
    same trees (model string minus params) and same raw scores, on both
    the EFB-bundled Expo shape and the unbundled HIGGS shape."""
    if shape == "higgs_unbundled":
        X, y = _higgs_small(3000)
    else:
        X, y = _expo_small(2048)
    base = {"objective": "binary", "num_leaves": 15, "max_depth": 4,
            "verbosity": -1, "min_data_in_leaf": 10, "max_bin": 63,
            "learning_rate": 0.2, **extra}
    bst_dev, c_dev = _train_counted(
        {**base, "tpu_persist_scan": "force"}, X, y, rounds=8)
    bst_host, c_host = _train_counted(
        {**base, "tpu_persist_scan": "off"}, X, y, rounds=8)
    # positive device-path pins (replacing the old inert assertions)
    assert c_dev.get("tree_learner::persist_scan_trees", 0) >= 8, c_dev
    assert c_dev.get("tree_learner::iter_launches", 0) > 0, c_dev
    assert c_host.get("tree_learner::persist_scan_trees", 0) == 0, c_host
    assert _trees_only(bst_dev) == _trees_only(bst_host)
    np.testing.assert_array_equal(_raw(bst_dev, X), _raw(bst_host, X))
