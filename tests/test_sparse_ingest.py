"""Streaming CSR ingest (BinnedDataset.from_sparse): bounded host memory,
parity with the dense path, wide-sparse training end to end.

Reference behavior: DatasetLoader streams sparse rows through PushOneRow
(src/io/dataset_loader.cpp:714-1004) without a dense staging matrix; EFB
bundles sparse features (dataset.cpp:97-234)."""
import numpy as np
import pytest

scipy_sparse = pytest.importorskip("scipy.sparse")

import lightgbm_tpu as lgb


def _sparse_data(n=5000, nf=300, density=0.02, seed=11):
    rng = np.random.default_rng(seed)
    X = scipy_sparse.random(n, nf, density=density, format="csr",
                            random_state=np.random.RandomState(seed),
                            data_rvs=lambda k: rng.normal(size=k))
    w = rng.normal(size=nf) * (rng.random(nf) < 0.1)
    y = (np.asarray(X @ w).ravel() + rng.normal(size=n) * 0.2 > 0).astype(
        np.float64)
    return X.tocsr(), y


def test_sparse_matches_dense_binning():
    # binning parity on the dense [N, G] layout — the ELL layout the
    # sparse path now auto-picks is covered by tests/test_multival.py
    X, y = _sparse_data()
    params = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
              "min_data_in_leaf": 5, "tpu_multival": "off"}
    ds_sp = lgb.Dataset(X, y, params=dict(params))
    ds_sp.construct()
    ds_dn = lgb.Dataset(np.asarray(X.todense()), y, params=dict(params))
    ds_dn.construct()
    a, b = ds_sp._inner, ds_dn._inner
    assert a.num_data == b.num_data
    assert a.used_features == b.used_features
    assert [m.num_bin for m in a.bin_mappers] == \
        [m.num_bin for m in b.bin_mappers]
    assert a.groups == b.groups
    assert np.array_equal(a.binned, b.binned)


def test_sparse_train_and_predict():
    X, y = _sparse_data()
    params = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
              "min_data_in_leaf": 5, "metric": "none"}
    bst = lgb.train(dict(params), lgb.Dataset(X, y), 10, verbose_eval=False)
    pred = bst.predict(np.asarray(X.todense()))
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, pred) > 0.7


def test_sparse_never_densifies(monkeypatch):
    """The full todense() must never be called on the whole matrix — only
    row chunks (bounded memory)."""
    X, y = _sparse_data(n=4000, nf=20000, density=0.002)
    max_rows = [0]
    orig = scipy_sparse.csr_matrix.todense

    def spy(self, *a, **k):
        max_rows[0] = max(max_rows[0], self.shape[0])
        return orig(self, *a, **k)
    monkeypatch.setattr(scipy_sparse.csr_matrix, "todense", spy)
    ds = lgb.Dataset(X, y, params={"verbosity": -1})
    ds.construct()
    assert max_rows[0] < 4000, "full matrix was densified"


def test_sparse_reference_alignment():
    X, y = _sparse_data()
    Xv, yv = _sparse_data(n=1000, seed=12)
    params = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
              "min_data_in_leaf": 5}
    ds = lgb.Dataset(X, y, params=dict(params))
    ds.construct()
    dv = lgb.Dataset(Xv, yv, params=dict(params), reference=ds)
    dv.construct()
    assert dv._inner.total_bins == ds._inner.total_bins
    assert dv._inner.groups == ds._inner.groups


@pytest.mark.slow  # tier-1 870s budget: cheaper sibling tests cover this area
def test_sparse_predict_chunked_matches_dense():
    """Booster.predict on scipy CSR streams row blocks (no whole-matrix
    densify; reference PredictForCSR analog) and matches dense predict."""
    sp = pytest.importorskip("scipy.sparse")
    rng = np.random.default_rng(5)
    n, f = 70_000, 400
    X = sp.random(n, f, density=0.01, format="csr", random_state=3,
                  data_rvs=lambda k: rng.normal(size=k))
    y = (np.asarray(X[:, 0].todense()).ravel()
         + np.asarray(X[:, 3].todense()).ravel() > 0.01).astype(float)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "max_bin": 63},
                    lgb.Dataset(X, y), 5, verbose_eval=False)
    # chunking engages: 32MB / (400*8B) ~ 10k rows per block < n
    p_sparse = bst.predict(X)
    p_dense = bst.predict(np.asarray(X[:20_000].todense(), np.float64))
    assert p_sparse.shape == (n,)
    np.testing.assert_allclose(p_sparse[:20_000], p_dense, rtol=1e-12)
    c = bst.predict(X[:15_000], pred_contrib=True)
    assert c.shape == (15_000, f + 1)
