"""4-bit nibble-packed HBM storage (the Dense4bitsBin analog,
src/io/dense_nbits_bin.hpp): pairs of <=16-bin groups share one storage
byte on device. Packing is a pure storage transform — models must be
IDENTICAL with it on and off, across both growers and mixed
narrow/wide/categorical/NaN features."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.data.dataset import BinnedDataset


def _narrow_wide_data(n=4000, seed=6):
    rng = np.random.default_rng(seed)
    wide = rng.normal(size=(n, 3))                       # 255-bin features
    narrow = rng.integers(0, 9, size=(n, 6)).astype(float)  # <=16-bin
    narrow[rng.random((n, 6)) < 0.05] = np.nan
    X = np.column_stack([wide, narrow])
    y = ((X[:, 0] > 0) ^ (np.nan_to_num(X[:, 3]) > 4)).astype(float)
    return X, y


def test_pack_plan_and_storage_width():
    X, y = _narrow_wide_data()
    cfg = lgb.Config({"max_bin": 255, "min_data_in_bin": 1,
                      "enable_bundle": False})
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    plan = ds.device_pack_plan(cfg)
    assert plan is not None
    storage_of, shift, n_storage, _mask = plan
    G = len(ds.groups)
    assert n_storage < G                       # pairs actually formed
    layout, meta = ds.to_device(cfg)
    assert ds.device_packed
    assert layout.bins.shape[1] == n_storage
    # unpacking the storage must reproduce the logical bin matrix
    import jax.numpy as jnp
    from lightgbm_tpu.ops.grow import _logical_bins
    logical = np.asarray(_logical_bins(layout.bins, layout, True))
    np.testing.assert_array_equal(logical, ds.binned.astype(np.int32))


@pytest.mark.parametrize("force_partitioned", [
    False,
    pytest.param(True, marks=pytest.mark.slow),  # tier-1 870s budget
])
def test_packed_model_identical(monkeypatch, force_partitioned):
    X, y = _narrow_wide_data()
    if force_partitioned:
        import lightgbm_tpu.treelearner.serial as s
        monkeypatch.setattr(s, "PARTITION_MIN_ROWS", 1000)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_bin": 1}
    m_on = lgb.train(dict(params), lgb.Dataset(X, y), 8,
                     verbose_eval=False).model_to_string()
    m_off = lgb.train(dict(params, tpu_4bit_packing=False),
                      lgb.Dataset(X, y), 8,
                      verbose_eval=False).model_to_string()
    assert m_on.split("parameters:")[0] == m_off.split("parameters:")[0]


@pytest.mark.slow  # tier-1 870s budget: cheaper sibling tests cover this area
def test_packed_with_categoricals_and_bundles():
    rng = np.random.default_rng(8)
    n = 3000
    cat = rng.integers(0, 5, n).astype(float)            # categorical, narrow
    sparse1 = (rng.random(n) < 0.04) * rng.integers(1, 4, n)   # EFB bundle
    sparse2 = (rng.random(n) < 0.04) * rng.integers(1, 4, n)
    wide = rng.normal(size=n)
    X = np.column_stack([wide, cat, sparse1, sparse2])
    y = ((wide > 0) | (cat == 2)).astype(float)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_bin": 1}
    p_on = lgb.train(dict(params), lgb.Dataset(X, y, categorical_feature=[1]),
                     8, verbose_eval=False).predict(X)
    p_off = lgb.train(dict(params, tpu_4bit_packing=False),
                      lgb.Dataset(X, y, categorical_feature=[1]), 8,
                      verbose_eval=False).predict(X)
    np.testing.assert_array_equal(p_on, p_off)
    acc = ((p_on > 0.5) == y).mean()
    assert acc > 0.95
