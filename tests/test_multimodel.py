"""Multi-model training subsystem (multimodel/) pins.

The contract under test: B boosters trained over ONE shared binned
Dataset through a model-axis vmap of the fused iteration are BIT-EXACT
vs the serial outer loop (one lgb.train per grid point), per-model knobs
riding as traced [B] inputs so the program count is independent of B.

  * B=1 vmapped-vs-scalar parity (model text + raw scores) on the
    unbundled HIGGS-like shape and the EFB-bundled Expo-like shape,
    across gbdt and goss;
  * B=4 sweep vs the serial loop with distinct learning rates AND
    bagging seeds (per-model bag masks as batched inputs);
  * active-mask inertness: an early-stopped lane freezes without
    perturbing its batchmates, and its truncated model matches serial;
  * engine.cv's device fast path (folds as lanes, per-fold bag masks
    over the full layout) reproduces the host fold loop bit-for-bit;
  * the compile-surface ladder (bucket_for / mm_ladder_bound) and the
    perf-gate registration of models_per_sec / sweep_compiles.

Batched-path assertions go through the tree_learner::mm_models counter:
parity would be trivially true if eligibility silently fell back to
serial, so every parity test first proves the vmapped path actually ran.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import multimodel
from lightgbm_tpu.data.synth import make_expo_like, make_higgs_like
from lightgbm_tpu.multimodel import batch, driver
from lightgbm_tpu.telemetry import events as telemetry

BASE = {"objective": "binary", "num_leaves": 15, "max_bin": 255,
        "verbosity": -1, "metric": "none", "learning_rate": 0.1}


@pytest.fixture(scope="module")
def higgs():
    X, y = make_higgs_like(2500)
    ds = lgb.Dataset(X, y, free_raw_data=False)
    ds.construct()
    return np.asarray(X), np.asarray(y), ds


@pytest.fixture(scope="module")
def expo():
    X, y = make_expo_like(2000, seed=3)
    ds = lgb.Dataset(X, y, free_raw_data=False)
    ds.construct()
    return np.asarray(X), np.asarray(y), ds


def _counted(fn, key="tree_learner::mm_models"):
    """Run ``fn`` with counters on; return (result, counter delta)."""
    was = telemetry.enabled()
    if not was:
        telemetry.enable("timers")
    c0 = telemetry.counts_snapshot().get(key, 0.0)
    try:
        out = fn()
        c1 = telemetry.counts_snapshot().get(key, 0.0)
    finally:
        if not was:
            telemetry.disable()
    return out, c1 - c0


def _assert_twin(swept, X, params, ds, rounds):
    """The swept booster must be bit-identical to its own serial loop."""
    ref = lgb.train(dict(params), ds, rounds, verbose_eval=False)
    assert swept.model_to_string() == ref.model_to_string()
    a = swept.predict(X, raw_score=True)
    b = ref.predict(X, raw_score=True)
    assert np.array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# B=1: the vmapped program vs the scalar one
# ---------------------------------------------------------------------------

def test_b1_parity_higgs_gbdt(higgs):
    X, y, ds = higgs
    out, d = _counted(
        lambda: multimodel.sweep([dict(BASE)], ds, num_boost_round=10))
    assert d == 1.0, "batched path did not run"
    _assert_twin(out[0], X, BASE, ds, 10)


@pytest.mark.slow  # extra goss step/grad program compiles
def test_b1_parity_higgs_goss(higgs):
    X, y, ds = higgs
    p = dict(BASE, boosting="goss")
    out, d = _counted(
        lambda: multimodel.sweep([p], ds, num_boost_round=10))
    assert d == 1.0, "batched path did not run"
    _assert_twin(out[0], X, p, ds, 10)


@pytest.mark.slow  # EFB-bundled layout compiles its own program family
@pytest.mark.parametrize("boosting", ["gbdt", "goss"])
def test_b1_parity_expo_bundled(expo, boosting):
    X, y, ds = expo
    p = dict(BASE, boosting=boosting)
    out, d = _counted(
        lambda: multimodel.sweep([p], ds, num_boost_round=8))
    assert d == 1.0, "batched path did not run"
    _assert_twin(out[0], X, p, ds, 8)


# ---------------------------------------------------------------------------
# B=4 sweep: distinct learning rates AND bagging seeds in one program
# ---------------------------------------------------------------------------

def test_sweep_b4_vs_serial_loop(higgs):
    X, y, ds = higgs
    grid = [dict(BASE, learning_rate=lr, bagging_fraction=0.7,
                 bagging_freq=1, bagging_seed=seed)
            for lr, seed in [(0.05, 1), (0.1, 2), (0.2, 3), (0.3, 4)]]
    out, d = _counted(
        lambda: multimodel.sweep(grid, ds, num_boost_round=10))
    assert d == 4.0, "batched path did not run for all 4 models"
    assert len(out) == 4
    texts = set()
    for bst, p in zip(out, grid):
        _assert_twin(bst, X, p, ds, 10)
        texts.add(bst.model_to_string())
    # the knobs really were per-model: four distinct models came back
    assert len(texts) == 4


@pytest.mark.slow  # compiles the fused 16-iteration block (k=16 + k=1 tail)
def test_sweep_b2_fused_block_vs_serial(higgs):
    X, y, ds = higgs
    grid = [dict(BASE, learning_rate=lr) for lr in (0.1, 0.25)]
    out, d = _counted(
        lambda: multimodel.sweep(grid, ds, num_boost_round=20))
    assert d == 2.0
    for bst, p in zip(out, grid):
        _assert_twin(bst, X, p, ds, 20)


def test_grid_expansion_and_group_identity(higgs):
    X, y, ds = higgs
    grid = multimodel.expand_grid(
        dict(BASE, learning_rate=[0.1, 0.2], min_gain_to_split=[0.0, 0.5]))
    assert len(grid) == 4
    assert sorted((g["learning_rate"], g["min_gain_to_split"])
                  for g in grid) == [(0.1, 0.0), (0.1, 0.5),
                                     (0.2, 0.0), (0.2, 0.5)]
    # traced knobs (learning_rate, min_gain_to_split) must NOT split the
    # static group: all four grid points share one compiled program chain
    members = [batch.Member(lgb.Booster(dict(p), ds), dict(p))
               for p in grid]
    kinds = [batch.eligibility(m) for m in members]
    assert all(k == ("scan", "") for k in kinds), kinds
    keys = {batch.group_key(m, "scan") for m in members}
    assert len(keys) == 1, "traced knobs leaked into the static group key"


# ---------------------------------------------------------------------------
# active-mask inertness: a stopped lane cannot perturb its batchmates
# ---------------------------------------------------------------------------

def test_active_mask_inertness(higgs):
    X, y, ds = higgs
    stopper = dict(BASE, min_gain_to_split=1e9)   # no split past iter 0
    normal = dict(BASE)
    out, d = _counted(
        lambda: multimodel.sweep([stopper, normal], ds,
                                 num_boost_round=10))
    assert d == 2.0
    # the stopper really stopped: constant tree 0, truncated at the
    # first round>=1 stub (same place the serial loop stops)
    assert out[0].model_to_string().count("Tree=") < 10
    _assert_twin(out[0], X, stopper, ds, 10)
    # ... and its frozen lane left the live batchmate untouched
    _assert_twin(out[1], X, normal, ds, 10)


# ---------------------------------------------------------------------------
# engine.cv device fast path: folds as lanes over the shared layout
# ---------------------------------------------------------------------------

def _run_cv(higgs, tpu_cv, nfold=3, rounds=8, **kw):
    X, y, ds_ = higgs
    ds = lgb.Dataset(X, label=kw.pop("label", y), free_raw_data=False)
    p = dict(BASE, seed=7, tpu_cv=tpu_cv)
    p.update(kw.pop("params", {}))
    return lgb.cv(p, ds, num_boost_round=rounds, nfold=nfold,
                  stratified=False, shuffle=True, seed=3, **kw)


def test_cv_device_parity(higgs):
    dev, d = _counted(lambda: _run_cv(
        higgs, "device", params={"metric": "auc"}))
    assert d == 3.0, "cv did not take the device fold-as-lane path"
    host = _run_cv(higgs, "off", params={"metric": "auc"})
    assert dev == host      # bitwise: same keys, same float lists


def test_cv_device_parity_bagged(higgs):
    bag = {"metric": "binary_logloss", "bagging_fraction": 0.6,
           "bagging_freq": 2, "bagging_seed": 11}
    dev, d = _counted(lambda: _run_cv(higgs, "device", params=bag))
    assert d == 3.0
    assert dev == _run_cv(higgs, "off", params=bag)


@pytest.mark.slow  # regression program family + three metric sets
def test_cv_device_parity_eval_train_metric(higgs):
    X, y, _ = higgs
    label = X[:, 0] * 2.0 + y
    p = {"objective": "regression", "metric": "l2"}
    dev, d = _counted(lambda: _run_cv(
        higgs, "device", nfold=4, label=label, params=p,
        eval_train_metric=True))
    assert d == 4.0
    host = _run_cv(higgs, "off", nfold=4, label=label, params=p,
                   eval_train_metric=True)
    assert dev == host
    assert any(k.startswith("train ") for k in dev)
    assert any(k.startswith("valid ") for k in dev)


@pytest.mark.slow  # trains to the early-stop point on both paths
def test_cv_device_early_stop_and_cvbooster(higgs):
    kw = dict(params={"metric": "binary_logloss", "learning_rate": 0.5,
                      "num_leaves": 7},
              rounds=30, early_stopping_rounds=3, return_cvbooster=True)
    dev, d = _counted(lambda: _run_cv(higgs, "device", **kw))
    assert d == 3.0
    host = _run_cv(higgs, "off", **kw)
    cbd, cbh = dev.pop("cvbooster"), host.pop("cvbooster")
    assert dev == host
    assert cbd.best_iteration == cbh.best_iteration
    assert len(cbd.boosters) == len(cbh.boosters) == 3
    for bd, bh in zip(cbd.boosters, cbh.boosters):
        # lane boosters ride the full train_set and carry tpu_cv in the
        # parameters dump, so header and tail differ; the trees
        # themselves must be bit-identical
        def trees(s):
            return s[s.index("Tree=0"):s.index("end of trees")]
        assert trees(bd.model_to_string()) == trees(bh.model_to_string())


def test_cv_off_never_touches_device_path(higgs):
    _, d = _counted(lambda: _run_cv(higgs, "off",
                                    params={"metric": "auc"}))
    assert d == 0.0


# ---------------------------------------------------------------------------
# compile-surface ladder + perf-gate registration
# ---------------------------------------------------------------------------

def test_bucket_ladder():
    assert [driver.bucket_for(b) for b in (1, 2, 3, 4, 5, 8, 9, 33, 64)] \
        == [1, 2, 4, 4, 8, 8, 16, 64, 64]
    with pytest.raises(ValueError):
        driver.bucket_for(0)
    with pytest.raises(ValueError):
        driver.bucket_for(driver.MM_MAX_BUCKET + 1)


def test_mm_ladder_bound_matches_bucket_count():
    from lightgbm_tpu.analysis import compile_audit
    buckets = {driver.bucket_for(b)
               for b in range(1, driver.MM_MAX_BUCKET + 1)}
    assert compile_audit.mm_ladder_bound() == len(buckets) == 7


def test_program_cache_is_bucket_keyed_not_width_keyed(higgs):
    """The program family is cached on the Dataset by compile-time key
    (never by B): a second sweep — even a wider one inside the same pow2
    bucket — registers zero new program families."""
    X, y, _ = higgs
    ds = lgb.Dataset(X, y, free_raw_data=False)   # fresh: empty cache
    ds.construct()
    grid3 = [dict(BASE, learning_rate=lr) for lr in (0.1, 0.15, 0.2)]
    _, d_cold = _counted(
        lambda: multimodel.sweep(grid3, ds, num_boost_round=4),
        key="tree_learner::mm_programs")
    _, d_warm = _counted(
        lambda: multimodel.sweep(grid3[:2] + [dict(BASE,
                                                   learning_rate=0.3),
                                              dict(BASE,
                                                   learning_rate=0.4)],
                                 ds, num_boost_round=4),
        key="tree_learner::mm_programs")
    assert d_cold >= 1.0          # the cold call built the program
    assert d_warm == 0.0          # B=3 and B=4 share the bucket-4 program


def test_perf_gate_registration():
    from lightgbm_tpu.analysis import perf_gate
    assert "models_per_sec" in perf_gate.HIGHER_BETTER
    assert "sweep_compiles" in perf_gate.LOWER_BETTER
    assert "sweep_compiles" in perf_gate.MEASUREMENT_CONDITIONAL
    assert "models_per_sec" not in perf_gate.MEASUREMENT_CONDITIONAL
