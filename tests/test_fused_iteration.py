"""Fused boosting iteration (PR 17): the cheap tier-1 pins.

The whole-iteration fusion folds the score update and the
gradient/hessian recompute into the per-tree compiled program
(ops/grow_persist.make_scan_driver), opening DART and RF to the device
fast path via per-tree weight vectors. This module pins the host-side
contracts that need no training run:

  * the ONE capability surface — `supports_fused_scan` and
    `persist_grad_mode` are derived views of `device_gradients()`,
    never independent flags;
  * the loud refusal when the config FORCES the fused path with a
    host-only objective (silent v1 fallback would diverge in launch
    count and, for quantized modes, in bits);
  * the stats-vector layout the drivers and the flush agree on
    (level_programs | fallback_splits | iter_launches | health...);
  * the perf-gate direction of the new `launches_per_iter` bench key.

The expensive halves — DART/RF bit-exact device-vs-host parity and the
launch-count pins on real training runs — live in test_level_grow.py
(slow-marked); the traced-program invariants (gradient kernels f64-free,
no host transfers between tree boundaries, payload aliasing) are the
`fused_iteration` auditor, exercised via test_analysis.py.
"""
from types import SimpleNamespace

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.objectives.base import create_objective


def _obj(name, **extra):
    cfg = Config({"objective": name, "verbosity": -1, **extra})
    return create_objective(name, cfg)


# ---------------------------------------------------------------------------
# the one capability surface
# ---------------------------------------------------------------------------

def test_device_gradient_capability_is_one_surface():
    rng = np.random.RandomState(0)
    cases = [
        ("binary", {}, (rng.rand(64) > 0.5).astype(np.float64)),
        ("regression", {}, rng.rand(64)),
        ("multiclass", {"num_class": 3},
         (np.arange(64) % 3).astype(np.float64)),
    ]
    for name, extra, label in cases:
        obj = _obj(name, **extra)
        obj.init(SimpleNamespace(label=label, weight=None), len(label))
        dg = obj.device_gradients()
        assert dg is not None and dg[0] == "payload", name
        assert callable(dg[1]), name
        # derived views, not independent flags
        assert obj.supports_fused_scan, name
        assert obj.persist_grad_mode() == "payload", name
        assert obj.persist_grad_args() == (), name


def test_host_only_objective_reports_none_everywhere():
    """rank_xendcg's per-iteration randomization needs fresh host
    inputs; the one surface must say so consistently."""
    obj = _obj("rank_xendcg")
    assert obj.device_gradients() is None
    assert not obj.supports_fused_scan
    assert obj.persist_grad_mode() == "row"


def test_mape_has_no_latent_payload_kernel():
    """MAPE's weights are recomputed per tree from the residual scale —
    inheriting L2's label-only payload kernel would silently train the
    wrong model. The override must refuse it."""
    obj = _obj("mape")
    obj.init(SimpleNamespace(label=np.abs(np.random.RandomState(1)
                                          .rand(32)) + 1.0,
                             weight=None), 32)
    assert obj.payload_grad_fn() is None


def test_forced_persist_with_host_only_objective_refuses_loudly():
    from lightgbm_tpu.data.dataset import BinnedDataset
    from lightgbm_tpu.treelearner.serial import SerialTreeLearner
    from lightgbm_tpu.utils.log import LightGBMError

    rng = np.random.RandomState(3)
    X = rng.rand(256, 4)
    y = (rng.rand(256) > 0.5).astype(np.float64)
    cfg = Config({"objective": "binary", "num_leaves": 7, "max_bin": 63,
                  "verbosity": -1, "tpu_persist_scan": "force"})
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    learner = SerialTreeLearner(cfg, ds)
    with pytest.raises(LightGBMError, match="no device gradient"):
        learner.can_persist_scan(_obj("rank_xendcg"))


# ---------------------------------------------------------------------------
# stats layout + perf-gate direction
# ---------------------------------------------------------------------------

def test_driver_stats_layout():
    from lightgbm_tpu.ops.grow_persist import (STAT_FALLBACK,
                                               STAT_HEALTH0,
                                               STAT_ITER_LAUNCH,
                                               STAT_LEVELS, STATS_LEN)
    assert (STAT_LEVELS, STAT_FALLBACK, STAT_ITER_LAUNCH) == (0, 1, 2)
    # the health tail starts right after the launch slot; the flush
    # (serial.flush_level_stats) and both drivers index off these
    assert STAT_HEALTH0 == 3
    assert STATS_LEN > STAT_HEALTH0


def test_launches_per_iter_gates_lower_better():
    from lightgbm_tpu.analysis import perf_gate
    assert "launches_per_iter" in perf_gate.LOWER_BETTER
    # telemetry-off rounds omit the counter snapshot; the key must not
    # sever the lineage when it vanishes for that reason
    assert "launches_per_iter" in perf_gate.MEASUREMENT_CONDITIONAL
