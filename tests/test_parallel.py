"""Multi-device (sharded) tree learner tests on a virtual 8-device CPU mesh.

The reference has NO automated distributed tests (SURVEY.md §4 — validated
manually via examples/parallel_learning); these tests are the coverage the
TPU rebuild adds: the data-parallel learner
(src/treelearner/data_parallel_tree_learner.cpp expressed as row sharding +
psum) must produce the same trees as the serial learner on the same data.
conftest.py provisions 8 virtual CPU devices.
"""
import numpy as np
import pytest

import jax

import lightgbm_tpu as lgb
from lightgbm_tpu.data.dataset import BinnedDataset
from lightgbm_tpu.parallel.learners import DataParallelTreeLearner, _make_mesh
from lightgbm_tpu.treelearner.serial import SerialTreeLearner


def _problem(n=3000, f=10, seed=11, with_missing=True):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    if with_missing:
        X[rng.random(size=n) < 0.1, 2] = np.nan
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 - X[:, 3] > 0.4).astype(np.float64)
    return X, y


def _grad_hess(ds, y, seed=5):
    # binary-logloss-like gradients at score 0
    p = 0.5
    grad = (p - y).astype(np.float64)
    hess = np.full_like(grad, p * (1 - p))
    return grad, hess


def _grow_pair(n=3000, num_leaves=31, **cfg_extra):
    import jax.numpy as jnp
    X, y = _problem(n=n)
    cfg = lgb.Config({"num_leaves": num_leaves, "objective": "binary",
                      "max_bin": 63, "min_data_in_leaf": 5, **cfg_extra})
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    grad, hess = _grad_hess(ds, y)
    g = jnp.asarray(grad, jnp.float32)
    h = jnp.asarray(hess, jnp.float32)
    bag = jnp.ones(ds.num_data, bool)

    serial = SerialTreeLearner(cfg, ds)
    t_serial, rl_serial = serial.train(g, h, bag)

    par = DataParallelTreeLearner(cfg, ds, mesh=_make_mesh(8))
    t_par, rl_par = par.train(g, h, bag)
    return t_serial, t_par, rl_serial, rl_par, ds, X


def test_eight_devices_available():
    assert len(jax.devices()) >= 8


def test_data_parallel_matches_serial_structure():
    t_s, t_p, rl_s, rl_p, ds, X = _grow_pair()
    assert t_p.num_leaves > 1
    assert t_s.num_leaves == t_p.num_leaves
    np.testing.assert_array_equal(t_s.split_feature, t_p.split_feature)
    np.testing.assert_array_equal(t_s.threshold_in_bin, t_p.threshold_in_bin)
    np.testing.assert_array_equal(np.asarray(rl_s), np.asarray(rl_p))
    np.testing.assert_allclose(t_s.leaf_value, t_p.leaf_value,
                               rtol=1e-6, atol=1e-9)


def test_data_parallel_nondivisible_rows():
    """Row counts that don't divide the mesh exercise the padding path."""
    t_s, t_p, rl_s, rl_p, ds, X = _grow_pair(n=3001)
    assert t_s.num_leaves == t_p.num_leaves
    np.testing.assert_array_equal(t_s.split_feature, t_p.split_feature)
    np.testing.assert_allclose(t_s.leaf_value, t_p.leaf_value,
                               rtol=1e-6, atol=1e-9)


@pytest.mark.slow  # 8-device shard_map compile: ~1 min on a 2-core CPU host
def test_train_end_to_end_data_parallel():
    """Full lgb.train with tree_learner=data matches serial predictions."""
    X, y = _problem(n=2000)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "max_bin": 63, "metric": "binary_logloss"}
    ds1 = lgb.Dataset(X, y)
    b_serial = lgb.train(dict(params), ds1, 10, verbose_eval=False)
    ds2 = lgb.Dataset(X, y)
    b_par = lgb.train(dict(params, tree_learner="data"), ds2, 10,
                      verbose_eval=False)
    p_s = b_serial.predict(X)
    p_p = b_par.predict(X)
    np.testing.assert_allclose(p_s, p_p, rtol=1e-5, atol=1e-8)


@pytest.mark.slow  # 8-device shard_map compile: ~1 min on a 2-core CPU host
def test_dryrun_multichip_entry():
    """The driver's multichip gate must run in-process on the 8-dev mesh."""
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g
    g.dryrun_multichip(8)
