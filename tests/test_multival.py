"""Multi-value (ELL row-sparse) device layout.

The TPU analog of the reference's MultiValBin / SparseBin
(src/io/multi_val_sparse_bin.hpp, sparse_bin.hpp): per-row (group, bin)
pairs for non-default bins, histograms as row-sparse scatter with every
feature's default-bin mass reconstructed by FixHistogram. Chosen
automatically for wide-sparse CSR ingest; forceable for testing via
tpu_multival=force.

Equality with the dense layout is to summation-order noise (~1e-6): the
ELL histogram accumulates in a different order and rebuilds most-freq
bins from leaf totals, exactly as the reference's multi-val path does.
"""
import numpy as np
import pytest
import scipy.sparse as sp

import lightgbm_tpu as lgb
from lightgbm_tpu.data.dataset import BinnedDataset


def _dense_data(n=3000, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    X[rng.random((n, f)) < 0.3] = 0.0
    X[rng.random((n, f)) < 0.05] = np.nan
    y = (X[:, 0] + 0.5 * np.nan_to_num(X[:, 1]) > 0.3).astype(float)
    return X, y


def _wide_sparse(n=4000, f=300, seed=1):
    """One-hot-ish wide matrix: ~8 active features per row."""
    rng = np.random.default_rng(seed)
    rows = np.repeat(np.arange(n), 8)
    cols = rng.integers(0, f, size=8 * n)
    vals = rng.normal(loc=1.0, size=8 * n)
    X = sp.csr_matrix((vals, (rows, cols)), shape=(n, f))
    beta = rng.normal(size=f) * (rng.random(f) < 0.2)
    y = (np.asarray(X @ beta).ravel() > 0).astype(float)
    return X, y


def test_forced_multival_matches_dense():
    X, y = _dense_data()
    base = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 20}
    b0 = lgb.train(dict(base), lgb.Dataset(X, y), 10, verbose_eval=False)
    p1 = dict(base, tpu_multival="force")
    ds1 = lgb.Dataset(X, y, params=p1)
    b1 = lgb.train(p1, ds1, 10, verbose_eval=False)
    assert ds1._inner.is_multival
    assert ds1._inner.binned is None
    np.testing.assert_allclose(b0.predict(X), b1.predict(X), atol=1e-4)


@pytest.mark.slow  # tier-1 870s budget: cheaper sibling tests cover this area
def test_forced_multival_matches_dense_regression_bundles():
    # EFB-bundled one-hot blocks + continuous features: sentinel groups
    # and single-feature groups both omit their default bins
    rng = np.random.default_rng(2)
    n = 2500
    onehot = np.zeros((n, 12))
    onehot[np.arange(n), rng.integers(0, 12, n)] = 1.0
    Xc = rng.normal(size=(n, 4))
    X = np.column_stack([Xc, onehot])
    y = Xc[:, 0] + onehot[:, 3] * 2.0 + 0.05 * rng.normal(size=n)
    base = {"objective": "regression", "num_leaves": 31, "verbosity": -1}
    b0 = lgb.train(dict(base), lgb.Dataset(X, y), 10, verbose_eval=False)
    p1 = dict(base, tpu_multival="force")
    b1 = lgb.train(p1, lgb.Dataset(X, y, params=p1), 10, verbose_eval=False)
    np.testing.assert_allclose(b0.predict(X), b1.predict(X), atol=1e-4)


@pytest.mark.slow  # tier-1 870s budget: cheaper sibling tests cover this area
def test_forced_multival_categorical():
    rng = np.random.default_rng(3)
    n = 2000
    Xc = rng.normal(size=(n, 3))
    cat = rng.integers(0, 7, size=n).astype(float)
    X = np.column_stack([Xc, cat])
    y = Xc[:, 0] + (cat == 3) * 1.5 + 0.05 * rng.normal(size=n)
    base = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
            "categorical_feature": [3]}
    b0 = lgb.train(dict(base), lgb.Dataset(
        X, y, categorical_feature=[3]), 10, verbose_eval=False)
    p1 = dict(base, tpu_multival="force")
    b1 = lgb.train(p1, lgb.Dataset(X, y, categorical_feature=[3],
                                   params=p1), 10, verbose_eval=False)
    np.testing.assert_allclose(b0.predict(X), b1.predict(X), atol=1e-4)


def test_sparse_auto_picks_multival_and_trains():
    X, y = _wide_sparse()
    ds = lgb.Dataset(X, y)
    b = lgb.train({"objective": "binary", "num_leaves": 31,
                   "verbosity": -1}, ds, 20, verbose_eval=False)
    inner = ds._inner
    assert inner.is_multival, "wide-sparse ingest should choose ELL"
    assert inner.binned is None, "dense [N, G] must never materialize"
    # ELL width is bounded by the true max active features per row
    assert inner.ell_grp.shape[1] <= 16
    pred = b.predict(np.asarray(X.todense()))
    acc = ((pred > 0.5) == y).mean()
    assert acc > 0.8


@pytest.mark.slow  # tier-1 870s budget: cheaper sibling tests cover this area
def test_sparse_multival_matches_sparse_dense_layout():
    # same CSR data, layouts forced both ways: same quality to noise
    X, y = _wide_sparse(n=2500, f=120)
    b0 = lgb.train({"objective": "binary", "num_leaves": 15,
                    "verbosity": -1, "tpu_multival": "off"},
                   lgb.Dataset(X, y, params={"tpu_multival": "off"}),
                   10, verbose_eval=False)
    b1 = lgb.train({"objective": "binary", "num_leaves": 15,
                    "verbosity": -1, "tpu_multival": "force"},
                   lgb.Dataset(X, y, params={"tpu_multival": "force"}),
                   10, verbose_eval=False)
    Xd = np.asarray(X.todense())
    np.testing.assert_allclose(b0.predict(Xd), b1.predict(Xd), atol=1e-4)


def test_multival_binary_cache_roundtrip(tmp_path):
    X, y = _wide_sparse(n=1500, f=100)
    params = {"tpu_multival": "force"}
    ds = lgb.Dataset(X, y, params=params)
    ds.construct()
    path = str(tmp_path / "mv.bin")
    ds._inner.save_binary(path)
    ds2 = BinnedDataset.from_binary(path)
    assert ds2.is_multival
    np.testing.assert_array_equal(ds._inner.ell_grp, ds2.ell_grp)
    np.testing.assert_array_equal(ds._inner.ell_bin, ds2.ell_bin)


def test_multival_continued_training_binned_walk():
    # init_model continuation exercises Tree.predict_leaf_binned over the
    # ELL host arrays (host_group_bins)
    X, y = _dense_data(n=1500)
    p = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
         "tpu_multival": "force"}
    ds = lgb.Dataset(X, y, params=p)
    b0 = lgb.train(dict(p), ds, 5, verbose_eval=False)
    b1 = lgb.train(dict(p), lgb.Dataset(X, y, params=p), 5,
                   verbose_eval=False, init_model=b0)
    r2 = 1 - np.var(y - b1.predict(X)) / np.var(y)
    assert r2 > 0.5


@pytest.mark.slow  # 8-device shard_map compile: ~1 min on a 2-core CPU host
def test_multival_sharded_matches_serial():
    """The ELL layout under the 8-device data-parallel mesh: the row-sparse
    arrays shard WITH the rows and the scatter histograms psum — trees
    match the serial multival run."""
    X, y = _dense_data(n=3000)
    base = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 20, "tpu_multival": "force"}
    b_s = lgb.train(dict(base), lgb.Dataset(X, y, params=base), 10,
                    verbose_eval=False)
    p_d = dict(base, tree_learner="data")
    ds_d = lgb.Dataset(X, y, params=p_d)
    b_d = lgb.train(p_d, ds_d, 10, verbose_eval=False)
    assert ds_d._inner.is_multival
    np.testing.assert_allclose(b_s.predict(X), b_d.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_multival_dense_row_falls_back_to_dense():
    # mean nnz/row is low but ONE row is fully dense: padding every row
    # to K=G would dwarf the dense matrix, so assembly must densify
    rng = np.random.default_rng(5)
    n, f = 3000, 150
    rows = np.repeat(np.arange(n), 4)
    cols = rng.integers(0, f, size=4 * n)
    X = sp.lil_matrix((n, f))
    X[rows, cols] = 1.0
    X[0, :] = np.arange(1, f + 1, dtype=float)   # one dense row
    X = X.tocsr()
    y = (np.asarray(X[:, 0].todense()).ravel() > 0).astype(float)
    p = {"tpu_multival": "auto", "min_data_in_leaf": 5}
    ds = lgb.Dataset(X, y, params=p)
    ds.construct()
    inner = ds._inner
    assert not inner.is_multival
    assert inner.binned is not None
    # and the densified matrix is identical to direct dense binning
    ds2 = lgb.Dataset(np.asarray(X.todense()), y,
                      params={"tpu_multival": "off", "min_data_in_leaf": 5})
    ds2.construct()
    np.testing.assert_array_equal(inner.binned, ds2._inner.binned)
