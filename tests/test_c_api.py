"""C API smoke test: drive the LGBM_* shared library via raw ctypes.

Analog of the reference's tests/c_api_test/test_.py, which dlopens
lib_lightgbm and exercises dataset creation, boosting, prediction, and
model IO through the C ABI. Here the library is the embedded-CPython shim
(lightgbm_tpu/native/c_api_shim.cpp) forwarding into lightgbm_tpu.c_api;
loading it from an already-running interpreter reuses that interpreter.
"""
import ctypes

import numpy as np
import pytest

from lightgbm_tpu.native import build_c_api

so_path = build_c_api()
if so_path is None:  # pragma: no cover - toolchain missing
    pytest.skip("C toolchain unavailable; cannot build c_api shim",
                allow_module_level=True)

LIB = ctypes.CDLL(so_path)
LIB.LGBM_GetLastError.restype = ctypes.c_char_p

C_API_DTYPE_FLOAT64 = 1
C_API_PREDICT_NORMAL = 0
C_API_PREDICT_CONTRIB = 3


def _check(rc):
    assert rc == 0, LIB.LGBM_GetLastError().decode()


def _make_data(n=400, f=5, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] - 0.2 * X[:, 2] > 0).astype(np.float32)
    return np.ascontiguousarray(X, dtype=np.float64), y


def _dataset_from_mat(X, y, params=b"max_bin=63 min_data_in_leaf=5"):
    handle = ctypes.c_void_p()
    _check(LIB.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
        ctypes.c_int32(X.shape[0]), ctypes.c_int32(X.shape[1]),
        ctypes.c_int(1), params, None, ctypes.byref(handle)))
    lab = np.ascontiguousarray(y, dtype=np.float32)
    _check(LIB.LGBM_DatasetSetField(
        handle, b"label", lab.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int(len(lab)), ctypes.c_int(0)))
    return handle


def test_dataset_create_and_fields():
    X, y = _make_data()
    handle = _dataset_from_mat(X, y)
    n = ctypes.c_int32()
    _check(LIB.LGBM_DatasetGetNumData(handle, ctypes.byref(n)))
    assert n.value == X.shape[0]
    _check(LIB.LGBM_DatasetGetNumFeature(handle, ctypes.byref(n)))
    assert n.value == X.shape[1]
    # get_field round trip
    out_len = ctypes.c_int32()
    out_ptr = ctypes.c_void_p()
    out_type = ctypes.c_int32()
    _check(LIB.LGBM_DatasetGetField(
        handle, b"label", ctypes.byref(out_len), ctypes.byref(out_ptr),
        ctypes.byref(out_type)))
    assert out_len.value == X.shape[0]
    got = np.ctypeslib.as_array(
        ctypes.cast(out_ptr, ctypes.POINTER(ctypes.c_float)),
        shape=(out_len.value,))
    np.testing.assert_allclose(got, y, rtol=1e-6)
    _check(LIB.LGBM_DatasetFree(handle))


def test_booster_train_predict_save_load(tmp_path):
    X, y = _make_data()
    ds = _dataset_from_mat(X, y)
    bst = ctypes.c_void_p()
    _check(LIB.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=15 learning_rate=0.2 verbosity=-1 "
            b"min_data_in_leaf=5 metric=binary_logloss",
        ctypes.byref(bst)))
    fin = ctypes.c_int32()
    for _ in range(12):
        _check(LIB.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
        if fin.value:
            break
    it = ctypes.c_int32()
    _check(LIB.LGBM_BoosterGetCurrentIteration(bst, ctypes.byref(it)))
    assert it.value >= 1
    # train-set eval through the C ABI
    cnt = ctypes.c_int32()
    _check(LIB.LGBM_BoosterGetEvalCounts(bst, ctypes.byref(cnt)))
    assert cnt.value >= 1
    vals = (ctypes.c_double * cnt.value)()
    got = ctypes.c_int32()
    _check(LIB.LGBM_BoosterGetEval(bst, 0, ctypes.byref(got), vals))
    assert got.value == cnt.value
    assert 0.0 < vals[0] < 0.7   # logloss actually improved over ln 2

    # predict for mat
    out_len = ctypes.c_int64()
    preds = np.zeros(X.shape[0], dtype=np.float64)
    _check(LIB.LGBM_BoosterPredictForMat(
        bst, X.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
        ctypes.c_int32(X.shape[0]), ctypes.c_int32(X.shape[1]),
        ctypes.c_int(1), ctypes.c_int(C_API_PREDICT_NORMAL),
        ctypes.c_int(0), b"", ctypes.byref(out_len),
        preds.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert out_len.value == X.shape[0]
    acc = np.mean((preds > 0.5) == (y > 0.5))
    assert acc > 0.85

    # SHAP through the C ABI sums to the raw score
    contrib_len = ctypes.c_int64()
    _check(LIB.LGBM_BoosterCalcNumPredict(
        bst, ctypes.c_int(X.shape[0]), ctypes.c_int(C_API_PREDICT_CONTRIB),
        ctypes.c_int(0), ctypes.byref(contrib_len)))
    assert contrib_len.value == X.shape[0] * (X.shape[1] + 1)
    contrib = np.zeros(contrib_len.value, dtype=np.float64)
    _check(LIB.LGBM_BoosterPredictForMat(
        bst, X.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
        ctypes.c_int32(X.shape[0]), ctypes.c_int32(X.shape[1]),
        ctypes.c_int(1), ctypes.c_int(C_API_PREDICT_CONTRIB),
        ctypes.c_int(0), b"", ctypes.byref(out_len),
        contrib.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    phi = contrib.reshape(X.shape[0], X.shape[1] + 1)
    raw = np.zeros(X.shape[0], dtype=np.float64)
    _check(LIB.LGBM_BoosterPredictForMat(
        bst, X.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
        ctypes.c_int32(X.shape[0]), ctypes.c_int32(X.shape[1]),
        ctypes.c_int(1), ctypes.c_int(1),   # RAW_SCORE
        ctypes.c_int(0), b"", ctypes.byref(out_len),
        raw.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    np.testing.assert_allclose(phi.sum(axis=1), raw, atol=1e-6)

    # save -> load -> identical predictions
    model_path = str(tmp_path / "c_api_model.txt").encode()
    _check(LIB.LGBM_BoosterSaveModel(bst, 0, -1, model_path))
    niter = ctypes.c_int32()
    bst2 = ctypes.c_void_p()
    _check(LIB.LGBM_BoosterCreateFromModelfile(
        model_path, ctypes.byref(niter), ctypes.byref(bst2)))
    assert niter.value == it.value
    preds2 = np.zeros(X.shape[0], dtype=np.float64)
    _check(LIB.LGBM_BoosterPredictForMat(
        bst2, X.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
        ctypes.c_int32(X.shape[0]), ctypes.c_int32(X.shape[1]),
        ctypes.c_int(1), ctypes.c_int(C_API_PREDICT_NORMAL),
        ctypes.c_int(0), b"", ctypes.byref(out_len),
        preds2.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    np.testing.assert_allclose(preds, preds2, rtol=1e-10)

    _check(LIB.LGBM_BoosterFree(bst))
    _check(LIB.LGBM_BoosterFree(bst2))
    _check(LIB.LGBM_DatasetFree(ds))


def test_csr_dataset_and_error_reporting():
    from scipy import sparse
    X, y = _make_data(n=300)
    Xs = sparse.csr_matrix(X)
    handle = ctypes.c_void_p()
    indptr = np.ascontiguousarray(Xs.indptr, dtype=np.int32)
    indices = np.ascontiguousarray(Xs.indices, dtype=np.int32)
    data = np.ascontiguousarray(Xs.data, dtype=np.float64)
    _check(LIB.LGBM_DatasetCreateFromCSR(
        indptr.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(2),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        data.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(1),
        ctypes.c_int64(len(indptr)), ctypes.c_int64(len(data)),
        ctypes.c_int64(X.shape[1]), b"max_bin=63", None,
        ctypes.byref(handle)))
    n = ctypes.c_int32()
    _check(LIB.LGBM_DatasetGetNumData(handle, ctypes.byref(n)))
    assert n.value == 300
    _check(LIB.LGBM_DatasetFree(handle))
    # invalid handle -> rc != 0 and an error message
    rc = LIB.LGBM_DatasetGetNumData(ctypes.c_void_p(999999),
                                    ctypes.byref(n))
    assert rc != 0
    assert b"handle" in LIB.LGBM_GetLastError().lower()


def test_custom_objective_update():
    X, y = _make_data()
    ds = _dataset_from_mat(X, y)
    bst = ctypes.c_void_p()
    _check(LIB.LGBM_BoosterCreate(
        ds, b"objective=none num_leaves=15 verbosity=-1 min_data_in_leaf=5",
        ctypes.byref(bst)))
    score = np.zeros(X.shape[0])
    fin = ctypes.c_int32()
    for _ in range(5):
        p = 1.0 / (1.0 + np.exp(-score))
        grad = np.ascontiguousarray(p - y, dtype=np.float32)
        hess = np.ascontiguousarray(p * (1 - p), dtype=np.float32)
        _check(LIB.LGBM_BoosterUpdateOneIterCustom(
            bst, grad.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            hess.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.byref(fin)))
        out_len = ctypes.c_int64()
        raw = np.zeros(X.shape[0], dtype=np.float64)
        _check(LIB.LGBM_BoosterPredictForMat(
            bst, X.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
            ctypes.c_int32(X.shape[0]), ctypes.c_int32(X.shape[1]),
            ctypes.c_int(1), ctypes.c_int(1), ctypes.c_int(0), b"",
            ctypes.byref(out_len),
            raw.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        score = raw
    acc = np.mean((score > 0) == (y > 0.5))
    assert acc > 0.8
    _check(LIB.LGBM_BoosterFree(bst))
    _check(LIB.LGBM_DatasetFree(ds))
