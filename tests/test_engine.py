"""End-to-end training tests.

Mirrors the reference test strategy (tests/python_package_test/test_engine.py):
per-objective training correctness on synthetic data with known structure,
early stopping, continued training, cv, pickling, missing values. Golden
expectations are behavioral (loss decreases to a threshold; exact structural
predictions on tiny crafted datasets) rather than bitwise.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _make_binary(n=1200, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + X[:, 1] ** 2 + 0.1 * rng.normal(size=n) > 0.8).astype(float)
    return X, y


def _make_regression(n=1200, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = 2.0 * X[:, 0] + np.sin(X[:, 1]) + 0.05 * rng.normal(size=n)
    return X, y


def test_binary():
    X, y = _make_binary()
    ds = lgb.Dataset(X, y)
    evals = {}
    b = lgb.train({"objective": "binary", "metric": "binary_logloss",
                   "num_leaves": 15, "verbosity": -1}, ds, 30,
                  valid_sets=[ds], valid_names=["train"],
                  evals_result=evals, verbose_eval=False)
    ll = evals["train"]["binary_logloss"]
    assert ll[-1] < 0.25
    assert ll[-1] < ll[0]
    p = b.predict(X)
    assert ((p > 0.5) == (y > 0)).mean() > 0.93


def test_regression():
    X, y = _make_regression()
    ds = lgb.Dataset(X, y)
    evals = {}
    lgb.train({"objective": "regression", "metric": "l2",
               "num_leaves": 31, "verbosity": -1}, ds, 30,
              valid_sets=[ds], evals_result=evals, verbose_eval=False)
    l2 = evals["training"]["l2"]
    assert l2[-1] < 0.25 * np.var(y)


def test_missing_value_handling():
    """Missing (NaN) rows route to the correct side (reference
    test_engine.py:117 family)."""
    rng = np.random.default_rng(3)
    X = rng.normal(size=(400, 3))
    X[:100, 0] = np.nan
    y = np.where(np.isnan(X[:, 0]), 1.0, (X[:, 0] > 0).astype(float))
    b = lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
                   "min_data_in_leaf": 1}, lgb.Dataset(X, y), 40,
                  verbose_eval=False)
    p = b.predict(X)
    assert ((p > 0.5) == (y > 0)).mean() > 0.98


def test_early_stopping():
    X, y = _make_binary()
    Xv, yv = _make_binary(seed=7)
    ds = lgb.Dataset(X, y)
    vs = lgb.Dataset(Xv, yv, reference=ds)
    b = lgb.train({"objective": "binary", "metric": "binary_logloss",
                   "num_leaves": 63, "verbosity": -1}, ds, 200,
                  valid_sets=[vs], early_stopping_rounds=5,
                  verbose_eval=False)
    assert 0 < b.best_iteration < 200


def test_continue_train():
    X, y = _make_regression()
    ds = lgb.Dataset(X, y)
    b1 = lgb.train({"objective": "regression", "num_leaves": 15,
                    "verbosity": -1}, ds, 10, verbose_eval=False)
    b2 = lgb.train({"objective": "regression", "num_leaves": 15,
                    "verbosity": -1}, lgb.Dataset(X, y), 10,
                   init_model=b1, verbose_eval=False)
    assert b2.num_trees() == 20
    mse1 = np.mean((y - b1.predict(X)) ** 2)
    mse2 = np.mean((y - b2.predict(X)) ** 2)
    assert mse2 < mse1


def test_continue_train_file_roundtrip_exact(tmp_path):
    """train 10 -> save -> init_model resume 10 == straight 20-iter model:
    same tree count AND bit-identical predictions (the graft seeds the
    score cache from the loaded trees' binned walk, so the resumed run
    grows the identical trees)."""
    X, y = _make_binary(n=600)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "min_data_in_leaf": 5}
    b20 = lgb.train(dict(params), lgb.Dataset(X, y, params=params), 20,
                    verbose_eval=False)
    b10 = lgb.train(dict(params), lgb.Dataset(X, y, params=params), 10,
                    verbose_eval=False)
    path = str(tmp_path / "init10.txt")
    b10.save_model(path)
    resumed = lgb.train(dict(params), lgb.Dataset(X, y, params=params), 10,
                        init_model=path, verbose_eval=False)
    assert resumed.num_trees() == b20.num_trees() == 20
    np.testing.assert_array_equal(resumed.predict(X, raw_score=True),
                                  b20.predict(X, raw_score=True))


def test_model_roundtrip(tmp_path):
    X, y = _make_binary()
    b = lgb.train({"objective": "binary", "num_leaves": 15, "verbosity": -1},
                  lgb.Dataset(X, y), 10, verbose_eval=False)
    path = str(tmp_path / "model.txt")
    b.save_model(path)
    b2 = lgb.Booster(model_file=path)
    np.testing.assert_allclose(b.predict(X, raw_score=True),
                               b2.predict(X, raw_score=True), rtol=1e-12)
    # converted predictions survive too (objective string parsed back)
    np.testing.assert_allclose(b.predict(X), b2.predict(X), rtol=1e-12)


def test_pickle():
    import pickle
    X, y = _make_binary()
    b = lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1},
                  lgb.Dataset(X, y), 5, verbose_eval=False)
    b2 = pickle.loads(pickle.dumps(b))
    np.testing.assert_allclose(b.predict(X, raw_score=True),
                               b2.predict(X, raw_score=True))


def test_multiclass():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(900, 6))
    y = np.argmax(X[:, :3] + 0.3 * rng.normal(size=(900, 3)), axis=1).astype(float)
    evals = {}
    b = lgb.train({"objective": "multiclass", "num_class": 3,
                   "metric": "multi_logloss", "num_leaves": 15,
                   "verbosity": -1}, lgb.Dataset(X, y), 25,
                  valid_sets=[lgb.Dataset(X, y, reference=None)],
                  evals_result=evals, verbose_eval=False)
    p = b.predict(X)
    assert p.shape == (900, 3)
    assert (np.argmax(p, 1) == y).mean() > 0.85


def test_multiclassova():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(600, 6))
    y = np.argmax(X[:, :3], axis=1).astype(float)
    b = lgb.train({"objective": "multiclassova", "num_class": 3,
                   "num_leaves": 7, "verbosity": -1},
                  lgb.Dataset(X, y), 20, verbose_eval=False)
    p = b.predict(X)
    assert (np.argmax(p, 1) == y).mean() > 0.85


@pytest.mark.parametrize("objective,tol", [
    ("regression_l1", 0.5), ("huber", 0.4), ("fair", 0.5),
    ("quantile", 0.6), ("mape", 0.6)])
def test_regression_objectives(objective, tol):
    X, y = _make_regression()
    y = y - y.min() + 1.0   # keep positive for mape stability
    b = lgb.train({"objective": objective, "num_leaves": 15,
                   "verbosity": -1}, lgb.Dataset(X, y), 40,
                  verbose_eval=False)
    mse = np.mean((y - b.predict(X)) ** 2)
    assert mse < tol * np.var(y), mse


@pytest.mark.parametrize("objective", ["poisson", "gamma", "tweedie"])
def test_positive_objectives(objective):
    rng = np.random.default_rng(9)
    X = rng.normal(size=(800, 5))
    y = np.exp(0.5 * X[:, 0] + 0.1 * rng.normal(size=800))
    b = lgb.train({"objective": objective, "num_leaves": 15,
                   "verbosity": -1}, lgb.Dataset(X, y), 40,
                  verbose_eval=False)
    p = b.predict(X)
    assert np.all(p > 0)
    # correlation with target is strong
    assert np.corrcoef(p, y)[0, 1] > 0.8


def test_xentropy():
    rng = np.random.default_rng(11)
    X = rng.normal(size=(600, 5))
    y = 1.0 / (1.0 + np.exp(-X[:, 0]))        # soft labels in [0,1]
    b = lgb.train({"objective": "cross_entropy", "num_leaves": 15,
                   "verbosity": -1}, lgb.Dataset(X, y), 30,
                  verbose_eval=False)
    p = b.predict(X)
    assert np.mean((p - y) ** 2) < 0.01


def test_goss_dart_rf():
    X, y = _make_binary(n=2000)
    common = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    accs = {}
    for boosting, extra in [
            ("goss", {}),
            ("dart", {"drop_rate": 0.2}),
            ("rf", {"bagging_freq": 1, "bagging_fraction": 0.7})]:
        params = dict(common, boosting=boosting, **extra)
        b = lgb.train(params, lgb.Dataset(X, y), 30, verbose_eval=False)
        p = b.predict(X)
        accs[boosting] = ((p > 0.5) == (y > 0)).mean()
    for k, acc in accs.items():
        assert acc > 0.9, (k, acc)


def test_bagging_and_feature_fraction():
    X, y = _make_binary(n=2000)
    b = lgb.train({"objective": "binary", "num_leaves": 15, "verbosity": -1,
                   "bagging_fraction": 0.6, "bagging_freq": 2,
                   "feature_fraction": 0.7}, lgb.Dataset(X, y), 30,
                  verbose_eval=False)
    p = b.predict(X)
    assert ((p > 0.5) == (y > 0)).mean() > 0.92


def test_lambdarank():
    rng = np.random.default_rng(13)
    n_queries, per_q = 60, 20
    n = n_queries * per_q
    X = rng.normal(size=(n, 5))
    rel = np.clip((X[:, 0] * 1.5 + 0.3 * rng.normal(size=n)), 0, None)
    y = np.minimum(rel.astype(int), 4).astype(float)
    group = np.full(n_queries, per_q)
    ds = lgb.Dataset(X, y, group=group)
    evals = {}
    b = lgb.train({"objective": "lambdarank", "metric": "ndcg",
                   "eval_at": [5], "num_leaves": 15, "verbosity": -1,
                   "min_data_in_leaf": 5},
                  ds, 30, valid_sets=[ds], evals_result=evals,
                  verbose_eval=False)
    ndcg = evals["training"]["ndcg@5"]
    assert ndcg[-1] > 0.80
    assert ndcg[-1] > ndcg[0]


def test_xendcg():
    rng = np.random.default_rng(13)
    n_queries, per_q = 60, 20
    n = n_queries * per_q
    X = rng.normal(size=(n, 5))
    y = np.minimum(np.clip(X[:, 0] * 1.5, 0, None).astype(int), 4).astype(float)
    group = np.full(n_queries, per_q)
    ds = lgb.Dataset(X, y, group=group)
    evals = {}
    lgb.train({"objective": "rank_xendcg", "metric": "ndcg", "eval_at": [5],
               "num_leaves": 15, "verbosity": -1, "min_data_in_leaf": 5},
              ds, 30, valid_sets=[ds], evals_result=evals, verbose_eval=False)
    assert evals["training"]["ndcg@5"][-1] > 0.80


def test_cv():
    X, y = _make_binary()
    r = lgb.cv({"objective": "binary", "metric": "auc", "verbosity": -1,
                "num_leaves": 7}, lgb.Dataset(X, y), 10, nfold=3,
               stratified=False)
    assert len(r["valid auc-mean"]) == 10
    assert r["valid auc-mean"][-1] > 0.9


def test_cv_lambdarank():
    """cv() with ranking objectives must propagate per-fold query groups
    (GroupKFold path) and not drop init_score in folds."""
    rng = np.random.default_rng(9)
    n_q, q_len = 40, 12
    n = n_q * q_len
    X = rng.normal(size=(n, 6))
    rel = np.clip((X[:, 0] + 0.5 * X[:, 1]
                   + 0.3 * rng.normal(size=n)) * 1.5 + 1.5, 0, 4)
    y = np.round(rel).astype(int)
    group = np.full(n_q, q_len)
    ds = lgb.Dataset(X, y, group=group, free_raw_data=False)
    r = lgb.cv({"objective": "lambdarank", "metric": "ndcg",
                "ndcg_eval_at": [3], "num_leaves": 7, "verbosity": -1,
                "min_data_in_leaf": 3}, ds, 8, nfold=4)
    key = [k for k in r if k.endswith("-mean")][0]
    assert len(r[key]) == 8
    assert r[key][-1] > 0.5


def test_subset_propagates_fields():
    X, y = _make_binary(n=600)
    w = np.linspace(0.5, 1.5, 600)
    isc = np.linspace(-0.1, 0.1, 600)
    group = np.full(60, 10)
    ds = lgb.Dataset(X, y, weight=w, group=group, init_score=isc,
                     free_raw_data=False)
    ds.construct()
    idx = np.arange(100, 300)
    sub = ds.subset(idx)
    sub.construct()
    np.testing.assert_allclose(sub.get_weight(), w[idx])
    np.testing.assert_allclose(sub.get_init_score(), isc[idx])
    assert np.sum(sub.get_group()) == 200
    np.testing.assert_array_equal(sub.get_group(), np.full(20, 10))


def test_custom_objective_and_metric():
    X, y = _make_binary()

    def fobj(preds, dtrain):
        labels = dtrain.get_label()
        p = 1.0 / (1.0 + np.exp(-preds))
        return p - labels, p * (1 - p)

    def feval(preds, dtrain):
        labels = dtrain.get_label()
        return "my_err", float(np.mean((preds > 0) != (labels > 0))), False

    evals = {}
    lgb.train({"objective": "none", "verbosity": -1, "num_leaves": 15},
              lgb.Dataset(X, y), 30, valid_sets=[lgb.Dataset(X, y)],
              fobj=fobj, feval=feval, evals_result=evals, verbose_eval=False)
    errs = evals["valid_0"]["my_err"]
    assert errs[-1] < 0.1


def test_monotone_constraints():
    """Compliance checker like reference test_engine.py:998."""
    rng = np.random.default_rng(17)
    n = 1500
    X = rng.uniform(size=(n, 3))
    y = (3 * X[:, 0] - 2 * X[:, 1] + 0.5 * rng.normal(size=n))
    b = lgb.train({"objective": "regression", "num_leaves": 31,
                   "verbosity": -1, "monotone_constraints": [1, -1, 0]},
                  lgb.Dataset(X, y), 30, verbose_eval=False)

    def is_monotone(b, feature, sign):
        grid = np.tile(np.array([0.5, 0.5, 0.5]), (50, 1))
        grid[:, feature] = np.linspace(0, 1, 50)
        p = b.predict(grid)
        d = np.diff(p)
        return np.all(sign * d >= -1e-10)
    assert is_monotone(b, 0, +1)
    assert is_monotone(b, 1, -1)


def test_weights():
    X, y = _make_regression(n=800)
    w = np.ones(800)
    w[:400] = 10.0
    b = lgb.train({"objective": "regression", "num_leaves": 15,
                   "verbosity": -1}, lgb.Dataset(X, y, weight=w), 20,
                  verbose_eval=False)
    pred = b.predict(X)
    mse_heavy = np.mean((y[:400] - pred[:400]) ** 2)
    assert mse_heavy < 0.3 * np.var(y)


def test_feature_importance():
    X, y = _make_regression()
    b = lgb.train({"objective": "regression", "num_leaves": 15,
                   "verbosity": -1}, lgb.Dataset(X, y), 10,
                  verbose_eval=False)
    imp_split = b.feature_importance("split")
    imp_gain = b.feature_importance("gain")
    assert imp_split.sum() > 0
    # features 0 and 1 carry all the signal
    assert imp_gain[0] + imp_gain[1] > 0.9 * imp_gain.sum()


def test_dump_model_json():
    X, y = _make_binary(n=300)
    b = lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1},
                  lgb.Dataset(X, y), 3, verbose_eval=False)
    import json
    d = b.dump_model()
    s = json.dumps(d)
    assert d["num_class"] == 1
    assert len(d["tree_info"]) == 3
    assert "tree_structure" in d["tree_info"][0]
