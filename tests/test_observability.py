"""Run-scale observability: streaming histograms (merge/percentile
contracts), the crash flight recorder, serving SLO metrics under an
open-loop Poisson load, cross-rank trace merge, and the Prometheus
snapshot — plus the telemetry-on overhead ceiling with histograms
enabled."""
import json
import math
import os
import sys
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import telemetry
from lightgbm_tpu.telemetry import events, export, flight, histo, merge
from lightgbm_tpu.telemetry.histo import Histogram

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


@pytest.fixture(autouse=True)
def _telemetry_clean():
    """Telemetry + flight state is process-global by design: every test
    starts and ends OFF, empty, disarmed."""
    events.disable()
    events.reset()
    events.set_out_path(None)
    flight.disarm()
    yield
    events.disable()
    events.reset()
    events.set_out_path(None)
    flight.disarm()


def _toy(n=400, f=8, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(float)
    return X, y


TOY_PARAMS = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
              "verbosity": -1, "metric": "none"}


# ---------------------------------------------------------------------------
# histograms: merge associativity + percentile error bound vs numpy
# ---------------------------------------------------------------------------

def test_histogram_percentile_error_bound_vs_numpy():
    """Quantile estimates stay within the documented relative bound
    (growth - 1) of the exact numpy percentiles, across a latency-shaped
    lognormal and a heavy uniform."""
    rng = np.random.default_rng(0)
    for vals in (rng.lognormal(-3.0, 1.0, 20_000),
                 rng.uniform(1e-4, 10.0, 20_000)):
        h = Histogram("t")
        for v in vals:
            h.record(v)
        assert h.count == len(vals)
        for q in (0.5, 0.95, 0.99, 0.999):
            est = h.percentile(q)
            ref = float(np.percentile(vals, q * 100))
            assert abs(est - ref) / ref <= (h.growth - 1.0) + 1e-9, \
                "p%g: est %g vs numpy %g" % (q * 100, est, ref)
        # extremes are exact (the min/max clamp)
        assert h.percentile(0.0) == float(vals.min())
        assert h.percentile(1.0) == float(vals.max())


def test_histogram_merge_associative_and_exact():
    rng = np.random.default_rng(1)
    vals = rng.lognormal(-2.0, 1.5, 9_000)
    parts = np.array_split(vals, 3)
    hs = []
    for part in parts:
        h = Histogram("x")
        for v in part:
            h.record(v)
        hs.append(h)
    a, b, c = hs
    left = a.copy().merge(b).merge(c)                 # (a+b)+c
    right = a.copy().merge(b.copy().merge(c))         # a+(b+c)
    assert left.to_dict() == right.to_dict()
    # merged == recorded-in-one-stream: the integer state (buckets,
    # counts, saturation) is EXACT; the float running sum matches to
    # addition-reordering rounding
    whole = Histogram("x")
    for v in vals:
        whole.record(v)
    dl, dw = left.to_dict(), whole.to_dict()
    tl, tw = dl.pop("total"), dw.pop("total")
    assert dl == dw
    assert abs(tl - tw) <= 1e-9 * abs(tw)


def test_histogram_roundtrip_layout_and_saturation():
    h = Histogram("s", lo=1e-6, hi=1e3, growth=1.1, unit="s")
    for v in (0.0, 1e-9, 0.5, -1.0, 5e3):
        h.record(v)
    # -1 underflows (negative), 5e3 overflows; 0 / 1e-9 clamp into
    # bucket 0 as legitimate below-resolution observations
    assert h.underflow == 1 and h.overflow == 1 and h.saturated == 2
    assert h.count == 5
    h2 = Histogram.from_dict(h.to_dict())
    assert h2.to_dict() == h.to_dict()
    with pytest.raises(ValueError):
        h.merge(Histogram("s", lo=1e-6, hi=1e3, growth=1.2))


def test_observe_registry_gated_on_telemetry():
    histo.observe("off::latency", 0.5)
    assert histo.histograms_snapshot() == {}
    events.enable("timers")
    histo.observe("on::latency", 0.5)
    histo.observe("on::latency", 1.5)
    snap = histo.histograms_snapshot()
    assert snap["on::latency"].count == 2
    assert abs(snap["on::latency"].total - 2.0) < 1e-12
    # events.reset clears the histogram registry with the rest
    events.reset()
    assert histo.histograms_snapshot() == {}


def test_report_and_metrics_surface_histograms_and_truncation(tmp_path,
                                                              monkeypatch):
    events.enable("timers")
    histo.observe("x::latency", 0.01)
    histo.observe("x::latency", 1e12)          # saturates (>= hi)
    report = telemetry.format_report()
    assert "x::latency" in report and "p99" in report
    assert "saturated" in report
    monkeypatch.setattr(events, "_dropped", 7)
    assert "7 trace event(s) dropped" in telemetry.format_report()
    path = str(tmp_path / "m.jsonl")
    telemetry.write_metrics_jsonl(path)
    lines = [json.loads(ln) for ln in open(path).read().splitlines()]
    header = lines[0]
    assert header["kind"] == "header"
    assert header["dropped_events"] == 7
    assert header["histo_saturation"] == 1
    hrows = [ln for ln in lines if ln["kind"] == "histogram"]
    assert len(hrows) == 1 and hrows[0]["name"] == "x::latency"
    # the jsonl histogram line round-trips into a mergeable Histogram
    h = Histogram.from_dict(hrows[0])
    assert h.count == 2 and h.overflow == 1


# ---------------------------------------------------------------------------
# collective guard: op-kind latency + bytes histograms at the guard
# ---------------------------------------------------------------------------

def test_guard_records_latency_and_bytes_histograms():
    from lightgbm_tpu.resilience import retry
    events.enable("timers")
    payload = np.zeros(1000, np.float64)
    out = retry.guard("allgather:smoke", lambda a: a * 2, payload)
    assert out.shape == payload.shape
    retry.guard("allreduce:smoke", lambda a: a, payload[:10])
    snap = histo.histograms_snapshot()
    lat = snap["collective::allgather::latency"]
    byt = snap["collective::allgather::bytes"]
    assert lat.count == 1 and lat.unit == "s"
    assert byt.count == 1 and byt.vmax == payload.nbytes
    assert snap["collective::allreduce::latency"].count == 1
    assert snap["collective::allreduce::bytes"].vmax == 80


def test_guard_failure_dumps_flight_record(tmp_path, monkeypatch):
    from lightgbm_tpu.resilience import retry
    from lightgbm_tpu.utils.log import LightGBMError
    events.enable("timers")
    flight.arm(dump_dir=str(tmp_path))
    monkeypatch.setattr(retry, "_POLICY",
                        retry.RetryPolicy(timeout_s=0, retries=1,
                                          backoff_s=0.0))

    def gone_peer():
        raise ConnectionError("peer vanished")

    with pytest.raises(LightGBMError):
        retry.guard("allgather:doomed", gone_peer)
    path = flight.last_dump_path()
    assert path is not None and os.path.exists(path)
    rec = json.loads(open(path).read())
    assert rec["reason"].startswith("collective_failed:allgather:doomed")
    kinds = {e["kind"] for e in rec["events"]}
    assert "collective_failed" in kinds
    assert rec["counters"].get("collective::retry") == 1
    # FAILED attempts count toward the latency distribution too (an
    # all-fast-successes histogram would lie about a crawling run)
    lat = histo.histograms_snapshot()["collective::allgather::latency"]
    assert lat.count == 2


# ---------------------------------------------------------------------------
# crash flight recorder on an injected kill
# ---------------------------------------------------------------------------

def test_injected_kill_leaves_readable_flight_dump(tmp_path):
    """tpu_fault_plan=kill@iter leaves an atomic flight.r0.json next to
    the checkpoints: recent spans/counter bumps, counter totals, and the
    kill event itself — the postmortem contract."""
    from lightgbm_tpu.resilience.faults import TrainingKilled
    X, y = _toy(n=300)
    ck = str(tmp_path / "ck")
    os.makedirs(ck)
    with pytest.raises(TrainingKilled):
        lgb.train(dict(TOY_PARAMS, tpu_telemetry="timers",
                       tpu_fault_plan="kill@iter=2",
                       checkpoint_dir=ck, snapshot_freq=1),
                  lgb.Dataset(X, y), 5, verbose_eval=False)
    path = os.path.join(ck, "flight.r0.json")
    assert os.path.exists(path)
    # atomic write: no orphaned tmp file beside the dump
    assert not [f for f in os.listdir(ck) if f.endswith(".tmp")]
    rec = json.loads(open(path).read())
    assert rec["format"] == "lightgbm_tpu.flight/1"
    assert rec["reason"] == "injected_kill@iter=2"
    assert rec["rank"] == 0
    kinds = {e["kind"] for e in rec["events"]}
    assert "kill" in kinds and "span" in kinds
    assert rec["counters"].get("faults::injected") == 1
    assert any(k.startswith("checkpoint::") for k in rec["counters"])


def test_flight_disarmed_records_and_dumps_nothing(tmp_path):
    events.enable("timers")
    with events.scope("x"):
        pass
    events.count("c")
    assert flight.snapshot() == []
    assert flight.dump("nope", path=str(tmp_path / "f.json")) is None
    assert not os.path.exists(str(tmp_path / "f.json"))


def test_flight_ring_is_bounded(tmp_path):
    events.enable("timers")
    flight.arm(dump_dir=str(tmp_path), capacity=64)
    for i in range(500):
        events.count("spin", 1)
    evs = flight.snapshot()
    assert len(evs) == 64                      # bounded, newest kept
    assert all(e["kind"] == "count" for e in evs)


# ---------------------------------------------------------------------------
# serving SLO: per-request latency/queue-wait + Poisson open loop
# ---------------------------------------------------------------------------

def _tiny_server(min_batch=64, max_batch=256):
    from lightgbm_tpu.predict import BatchServer
    X, y = _toy(n=600)
    bst = lgb.train(dict(TOY_PARAMS), lgb.Dataset(X, y), 5,
                    verbose_eval=False)
    bst._booster._materialize_pending()
    server = BatchServer(bst._booster.device_predictor(),
                         min_batch=min_batch, max_batch=max_batch)
    b = server.min_batch
    while b <= server.max_batch:
        server.predict(X[:b])
        b <<= 1
    return server, X


def test_batchserver_latency_and_queue_wait_histograms():
    server, X = _tiny_server()
    warm = server.stats()["requests"]
    server.predict(X[:100])
    server.predict(X[:50], arrival_t=time.perf_counter() - 0.02)
    st = server.stats()
    assert st["requests"] == warm + 2
    assert st["latency_p50"] <= st["latency_p99"]
    assert st["latency"]["count"] == st["requests"]
    # the backdated arrival shows up as queue wait >= 20ms
    assert st["queue_wait"]["max"] >= 0.02
    assert st["queue_wait_p99"] >= 0.0
    # telemetry mirror only when enabled (it was off here)
    assert histo.histograms_snapshot() == {}
    events.enable("timers")
    server.predict(X[:10])
    assert histo.histograms_snapshot()["predict::e2e_latency"].count == 1


def test_poisson_open_loop_bench_smoke():
    """The BENCH predict SLO generator on a toy server: pinned key set
    and p50 <= p99 (plus sane queue-depth accounting)."""
    import bench
    server, X = _tiny_server()
    rng = np.random.default_rng(11)
    out = bench.poisson_open_loop(server, X, rps=200.0, n_requests=40,
                                  rng=rng, batch_lo=16, batch_hi=64)
    assert set(out) == {"requests", "rps", "p50", "p99",
                       "queue_wait_p99", "qdepth_mean", "qdepth_max"}
    assert out["requests"] == 40
    assert 0.0 < out["p50"] <= out["p99"]
    assert out["qdepth_mean"] >= 1.0          # the in-service request
    assert out["qdepth_max"] >= out["qdepth_mean"]
    assert out["queue_wait_p99"] >= 0.0


# ---------------------------------------------------------------------------
# cross-rank trace merge
# ---------------------------------------------------------------------------

def _rank_trace(rank, skew_us, tmp_path):
    """Synthesize one rank's chrome trace: two collective barrier spans
    (the alignment anchors) plus a rank-local span, all shifted by this
    rank's clock skew — and one collective-category LAUNCH span whose
    end skews wildly per rank (async dispatch is not a rendezvous; it
    must never anchor the alignment)."""
    evs = []
    for i, (name, t0, dur) in enumerate([
            ("collective::Allgather(binning,DCN)", 1_000.0, 400.0),
            ("work::local", 2_000.0 + rank * 37, 500.0),
            ("collective::multihost_scan(launch)", 3_000.0,
             200.0 + rank * 50_000.0),
            ("collective::AllreduceMean(metrics,DCN)", 5_000.0, 300.0)]):
        cat = "collective" if name.startswith("collective") else "misc"
        evs.append({"name": name, "cat": cat, "ph": "X",
                    "ts": t0 + skew_us, "dur": dur, "pid": rank,
                    "tid": 100 + rank})
    trace = {"traceEvents": evs, "displayTimeUnit": "ms",
             "otherData": {"producer": "test", "dropped_events": rank,
                           "process_index": rank}}
    path = str(tmp_path / ("run.r%d.json" % rank))
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


def test_two_rank_trace_merge_aligns_and_is_deterministic(tmp_path):
    _rank_trace(0, 0.0, tmp_path)
    _rank_trace(1, 5_000.0, tmp_path)          # rank 1's clock runs 5ms ahead
    summary = merge.merge_dir(str(tmp_path))
    out_path = summary["out"]
    assert summary["ranks"] == [0, 1]
    # the barrier-span alignment recovered the skew exactly
    assert abs(summary["clock_offsets_us"]["1"] + 5_000.0) < 1e-6
    assert summary["clock_offsets_us"]["0"] == 0.0
    assert summary["dropped_events"] == 1
    merged = json.loads(open(out_path).read())
    evs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    # one valid chrome trace: complete events with the required keys,
    # rank-tagged pids, and rank-1 barriers now co-timed with rank 0's
    for e in evs:
        assert {"name", "cat", "ph", "ts", "dur", "pid", "tid"} <= set(e)
    assert {e["pid"] for e in evs} == {0, 1}
    barr = [e for e in evs if e["cat"] == "collective"
            and not e["name"].endswith("(launch)")]
    by_name = {}
    for e in barr:
        by_name.setdefault(e["name"], []).append(e["ts"] + e["dur"])
    for ends in by_name.values():
        assert len(ends) == 2 and abs(ends[0] - ends[1]) < 1e-6
    meta = [e for e in merged["traceEvents"] if e.get("ph") == "M"]
    assert {m["args"]["name"] for m in meta} == {"rank 0", "rank 1"}
    # determinism: re-merging the same inputs is byte-identical
    blob1 = open(out_path, "rb").read()
    merge.merge_dir(str(tmp_path), )
    assert open(out_path, "rb").read() == blob1


def test_merge_cli_entry(tmp_path, capsys):
    from lightgbm_tpu.profile import main
    _rank_trace(0, 0.0, tmp_path)
    _rank_trace(1, -2_500.0, tmp_path)
    assert main(["--merge", str(tmp_path), "--json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["ranks"] == [0, 1]
    assert os.path.exists(summary["out"])
    # empty dir fails loudly, not silently
    empty = tmp_path / "empty"
    empty.mkdir()
    assert main(["--merge", str(empty)]) == 2


def test_merge_refuses_mixed_run_directories(tmp_path):
    """Rank files from two different runs (different basenames) must not
    silently combine into a plausible-looking garbage trace."""
    _rank_trace(0, 0.0, tmp_path)
    other = json.loads((tmp_path / "run.r0.json").read_text())
    with open(str(tmp_path / "archive.r1.json"), "w") as f:
        json.dump(other, f)
    with pytest.raises(merge.MergeError, match="more than one run"):
        merge.merge_dir(str(tmp_path))


def test_rank_suffix_single_host_unchanged():
    # single-process runs keep their exact telemetry_out path (the
    # multihost suffix seam is covered by the two-process slow test)
    assert export.rank_suffixed("/tmp/x/out.json") == "/tmp/x/out.json"
    assert export.process_index() == 0


# ---------------------------------------------------------------------------
# Prometheus snapshot
# ---------------------------------------------------------------------------

def test_prom_snapshot_written_and_parseable(tmp_path):
    from lightgbm_tpu.telemetry import promexport
    events.enable("timers")
    with events.scope("boosting::X", category="boosting"):
        pass
    events.count("predict::served", 3)
    histo.observe("predict::e2e_latency", 0.012)
    path = str(tmp_path / "snap.prom")
    promexport.write_prom(path)
    text = open(path).read()
    assert 'lgbtpu_timer_seconds_total{name="boosting::X"' in text
    assert 'lgbtpu_counter_total{name="predict::served"} 3' in text
    assert 'lgbtpu_histo{name="predict::e2e_latency",quantile="0.99"}' \
        in text
    assert "lgbtpu_histo_count" in text and "lgbtpu_dropped_events" \
        in text
    # native-histogram form: cumulative le-buckets (rate()/average
    # queries + cross-rank histogram_quantile need these, the summary
    # quantile gauges cannot provide them)
    assert "# TYPE lgbtpu_histo_dist histogram" in text
    histo.observe("other::latency", 3.5)   # very different value range

    def _les(name):
        pre = 'lgbtpu_histo_dist_bucket{name="%s"' % name
        return [ln.split('le="')[1].split('"')[0]
                for ln in promexport.render().splitlines()
                if ln.startswith(pre)]
    bucket_lines = [ln for ln in text.splitlines()
                    if ln.startswith('lgbtpu_histo_dist_bucket'
                                     '{name="predict::e2e_latency"')]
    assert bucket_lines, "per-histogram _bucket lines missing"
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in bucket_lines]
    assert counts == sorted(counts), "cumulative buckets must be " \
        "monotone"
    assert bucket_lines[-1].rsplit(" ", 1) == [
        'lgbtpu_histo_dist_bucket{name="predict::e2e_latency",'
        'le="+Inf"}', "1"]
    assert 'lgbtpu_histo_dist_count{name="predict::e2e_latency"} 1' \
        in text
    assert 'lgbtpu_histo_dist_sum{name="predict::e2e_latency"}' in text
    # the le ladder is a function of the LAYOUT, not the data — every
    # histogram (and so every rank) exposes the identical edge set,
    # the precondition for sum(rate(_bucket)) by (le) aggregation
    assert _les("predict::e2e_latency") == _les("other::latency")
    assert len(_les("other::latency")) > 10
    # every sample line is NAME{labels} VALUE with a float-parseable value
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        name, val = line.rsplit(" ", 1)
        float(val)
    assert not [f for f in os.listdir(str(tmp_path))
                if f.endswith(".tmp")]


def test_prom_flush_via_telemetry_out(tmp_path):
    """telemetry_out=...prom: training flushes a scrapeable snapshot
    (the final maybe_export write; the periodic path is the same
    function behind a throttle)."""
    X, y = _toy(n=300)
    out = str(tmp_path / "run.prom")
    lgb.train(dict(TOY_PARAMS, tpu_telemetry="timers", telemetry_out=out),
              lgb.Dataset(X, y), 3, verbose_eval=False)
    text = open(out).read()
    assert "lgbtpu_timer_seconds_total" in text
    assert 'name="boosting::TrainOneIter"' in text


# ---------------------------------------------------------------------------
# overhead ceiling with histograms enabled (the PR 1 pattern)
# ---------------------------------------------------------------------------

def test_histogram_observe_overhead_ceiling():
    """Recording is O(1) and allocation-free: 20k observes (timers mode,
    flight armed — the worst instrumented configuration) stay under a
    coarse wall ceiling, so per-collective/per-request recording can
    never dominate the operations it measures."""
    events.enable("timers")
    flight.arm(dump_dir=".")
    t0 = time.perf_counter()
    for i in range(20_000):
        histo.observe("hot::latency", 1e-4 * (1 + (i & 7)))
    elapsed = time.perf_counter() - t0
    assert elapsed < 1.0, "20k observes took %.3fs" % elapsed
    h = histo.histograms_snapshot()["hot::latency"]
    assert h.count == 20_000 and h.saturated == 0


# ---------------------------------------------------------------------------
# two-process end-to-end: injected-kill multihost run leaves per-rank
# flight dumps + rank-suffixed traces, and profile --merge unifies them
# ---------------------------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MH_KILL_WORKER = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, %(repo)r)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:   # jax 0.4.x: the XLA_FLAGS above covers it
    pass
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except AttributeError:
    pass

rank = int(sys.argv[1])
port = sys.argv[2]
outdir = sys.argv[3]
os.environ["JAX_PROCESS_ID"] = str(rank)

import lightgbm_tpu as lgb
from lightgbm_tpu.resilience.faults import TrainingKilled

rng = np.random.default_rng(11)
n, nf = 2400, 6
X = rng.normal(size=(n, nf))
y = (X[:, 1] + 0.5 * X[:, 4] + rng.normal(size=n) * 0.3 > 0).astype(float)

params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "num_machines": 2,
          "machines": "127.0.0.1:%%s,127.0.0.1:0" %% port,
          "min_data_in_leaf": 5, "tree_learner": "data",
          "tpu_telemetry": "trace",
          "telemetry_out": os.path.join(outdir, "mh.json"),
          "checkpoint_dir": outdir, "snapshot_freq": 4,
          "tpu_fault_plan": "kill@iter=8"}
try:
    lgb.train(params, lgb.Dataset(X, y), num_boost_round=12,
              verbose_eval=False)
except TrainingKilled:
    sys.exit(0)
sys.exit(3)   # the kill must fire
"""


@pytest.mark.slow
def test_multihost_kill_leaves_flight_dumps_and_mergeable_traces(tmp_path):
    """The acceptance path end to end: a two-rank run with an injected
    kill leaves (a) an atomic flight dump per rank next to its
    checkpoints and (b) rank-suffixed Chrome traces that
    `profile --merge` unifies into one valid trace."""
    import socket
    import subprocess

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    script = tmp_path / "mh_kill_worker.py"
    script.write_text(MH_KILL_WORKER % {"repo": REPO})
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(script), str(r), str(port),
             str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    for p in procs:
        try:
            _, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost kill worker timed out")
        assert p.returncode == 0, err.decode()[-2000:]

    # (a) per-rank atomic flight dumps, readable, with the kill recorded
    for r in range(2):
        fpath = tmp_path / ("flight.r%d.json" % r)
        assert fpath.exists(), sorted(os.listdir(str(tmp_path)))
        rec = json.loads(fpath.read_text())
        assert rec["rank"] == r
        assert rec["reason"] == "injected_kill@iter=8"
        assert any(e["kind"] == "kill" for e in rec["events"])
        # the guard-recorded collectives made it into the ring and the
        # histograms: every DCN kind that ran has latency+bytes
        coll = [e for e in rec["events"] if e["kind"] == "collective"]
        assert coll, "no collective events in the flight ring"
        for e in coll[:3]:
            assert "dur" in e and "bytes" in e
        kinds = {e["op"] for e in coll}
        for k in kinds:
            assert "collective::%s::latency" % k in rec["histograms"]
            assert "collective::%s::bytes" % k in rec["histograms"]
    assert not [f for f in os.listdir(str(tmp_path))
                if f.endswith(".tmp")]

    # (b) rank-suffixed traces (the telemetry_out collision fix) merge
    # into one valid chrome trace via the CLI seam
    assert (tmp_path / "mh.r0.json").exists(), \
        sorted(os.listdir(str(tmp_path)))
    assert (tmp_path / "mh.r1.json").exists()
    summary = merge.merge_dir(str(tmp_path))
    assert summary["ranks"] == [0, 1]
    merged = json.loads(open(summary["out"]).read())
    evs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    assert {e["pid"] for e in evs} == {0, 1}
    for e in evs[:50]:
        assert {"name", "cat", "ph", "ts", "dur", "pid", "tid"} <= set(e)
    # both ranks contributed collective barrier spans for alignment
    assert summary["barrier_spans"][0] > 0
    assert summary["barrier_spans"][1] > 0


def test_training_with_histograms_off_leaves_no_trace():
    """tpu_telemetry off (the default): the histogram registry stays
    empty through a full train + serve — the no-op-when-off guarantee
    extends to the new subsystem."""
    X, y = _toy(n=400)
    bst = lgb.train(dict(TOY_PARAMS), lgb.Dataset(X, y), 4,
                    verbose_eval=False)
    from lightgbm_tpu.predict import BatchServer
    bst._booster._materialize_pending()
    server = BatchServer(bst._booster.device_predictor(), min_batch=64,
                         max_batch=128)
    server.predict(X[:80])
    assert histo.histograms_snapshot() == {}
    assert flight.snapshot() == []
