"""Seed-determined chaos tier: random-but-replayable fault schedules.

Every schedule is a pure function of its seed (zlib.crc32 arithmetic,
same determinism contract as faults.py — no RNG object, no clock), so a
failing chaos run replays identically from its seed. The cheap smoke
(single-host kill/resize/corrupt schedules + a guard-level stall
schedule) runs in tier-1 under the ``chaos`` marker; the real
two-process world=2 schedule — kill + straggler stall + elastic resume
onto world=1 — is the slow sibling at the bottom.
"""
import json
import os
import shutil
import socket
import subprocess
import sys
import zlib

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.resilience import checkpoint as ckpt
from lightgbm_tpu.resilience import faults, retry
from lightgbm_tpu.resilience.faults import FaultPlan, TrainingKilled

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.chaos


def _h(seed: int, field: bytes) -> int:
    # one crc PER FIELD: bit-slices of a single crc correlate across
    # adjacent seeds (crc32 is linear in its input)
    return zlib.crc32(b"lgbtpu-chaos:%s:%d" % (field, seed))


def chaos_schedule(seed: int) -> dict:
    """The seed's fault schedule: which verb kills the run, when, what
    rides along. Pure integer arithmetic on crcs — replayable forever."""
    kill_iter = 3 + _h(seed, b"kill") % 9       # 3..11
    freq = 2 + _h(seed, b"freq") % 3            # snapshot_freq 2..4
    resize = _h(seed, b"resize") % 2 == 0       # resize@ vs kill@
    corrupt = _h(seed, b"corrupt") % 2 == 0     # poison the 1st snapshot
    plan = ("resize@iter=%d;world=2" % kill_iter if resize
            else "kill@iter=%d" % kill_iter)
    if corrupt:
        plan += ",corrupt_checkpoint@n=1"
    return {"seed": seed, "kill_iter": kill_iter, "freq": freq,
            "resize": resize, "corrupt": corrupt, "plan": plan,
            "stall_round": 1 + _h(seed, b"stall") % 3,
            "stall_secs": 1}


def test_schedules_are_deterministic_and_diverse():
    a = [chaos_schedule(s) for s in range(16)]
    b = [chaos_schedule(s) for s in range(16)]
    assert a == b
    # the seed space actually exercises every verb combination
    assert any(s["resize"] for s in a) and any(not s["resize"] for s in a)
    assert any(s["corrupt"] for s in a) and any(not s["corrupt"] for s in a)
    assert len({s["freq"] for s in a}) >= 2


def _make_binary(n=900, nf=6, seed=0):
    # identical shape/params to test_resilience: the chaos trains reuse
    # the same compiled programs inside the tier-1 process
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, nf))
    y = (X[:, 0] - 0.5 * X[:, 2] + rng.normal(size=n) * 0.3 > 0)
    return X, y.astype(float)


BASE = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
        "min_data_in_leaf": 5, "learning_rate": 0.3,
        "bagging_fraction": 0.8, "bagging_freq": 2,
        "feature_fraction": 0.7}


# seeds chosen so tier-1 drives one plain kill@ and one
# resize@+corrupt_checkpoint@ schedule (the diversity test above proves
# the space; these pin the paths cheaply)
@pytest.mark.parametrize("seed", [0, 4])
def test_chaos_kill_resume_single_host(tmp_path, seed):
    """One seed-determined schedule end to end: train, die at the
    scheduled point (kill or resize, maybe through a corrupted
    snapshot), resume, finish bit-exact with the uninterrupted run."""
    sched = chaos_schedule(seed)
    X, y = _make_binary()
    d = str(tmp_path / ("chaos%d" % seed))
    os.makedirs(d)
    params = dict(BASE, snapshot_freq=sched["freq"], checkpoint_dir=d)
    model_a = lgb.train(dict(params), lgb.Dataset(X, y), 12,
                        verbose_eval=False).model_to_string(
        num_iteration=-1)
    shutil.rmtree(d)
    os.makedirs(d)
    with pytest.raises(TrainingKilled) as exc:
        lgb.train(dict(params, tpu_fault_plan=sched["plan"]),
                  lgb.Dataset(X, y), 12, verbose_eval=False)
    if sched["resize"]:
        assert exc.value.target_world == 2
    # the scheduled death left only boundary-aligned snapshots behind
    snaps = [i for i, _ in ckpt.list_checkpoints(d)]
    assert all(i % sched["freq"] == 0 and i <= sched["kill_iter"]
               for i in snaps)
    resumed = lgb.train(dict(params), lgb.Dataset(X, y), 12,
                        verbose_eval=False)
    assert resumed.num_trees() == 12
    assert resumed.model_to_string(num_iteration=-1) == model_a


@pytest.mark.parametrize("seed", [1, 2])
def test_chaos_stall_schedule_guard_level(seed):
    """The stall half of a schedule, driven through the guard directly:
    exactly the scheduled round stalls, the soft watchdog counts it,
    every call still succeeds."""
    from lightgbm_tpu import telemetry
    from lightgbm_tpu.telemetry import flight
    sched = chaos_schedule(seed)
    telemetry.enable("timers")
    # a previous test may have left the flight recorder armed at the
    # cwd; the stall path dumps whenever armed, so disarm explicitly
    flight.disarm()
    try:
        telemetry.reset()
        retry.reset_rounds()
        faults._PLAN = FaultPlan("stall@round=%d;secs=%d"
                                 % (sched["stall_round"],
                                    sched["stall_secs"]))
        retry._POLICY = retry.RetryPolicy(timeout_s=30.0, retries=0,
                                          backoff_s=0.0,
                                          soft_timeout_s=0.1)
        for r in range(1, 4):
            assert retry.guard("allgather:chaos%d" % r,
                               lambda r=r: r) == r
        counts = telemetry.events.counts_snapshot()
        assert counts.get("collective::stall", 0) == 1, counts
        assert counts.get("collective::timeout", 0) == 0, counts
    finally:
        faults.reset()
        retry._POLICY = retry.RetryPolicy()
        telemetry.reset()
        telemetry.disable()


# ---------------------------------------------------------------------------
# the real thing (slow): two-process world=2 chaos schedule — straggler
# stall mid-run, scheduled death, elastic resume onto world=1
# ---------------------------------------------------------------------------

CHAOS_WORKER = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, %(repo)r)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    pass
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except AttributeError:
    pass

rank = int(sys.argv[1])
port = sys.argv[2]
out = sys.argv[3]
ckdir = sys.argv[4]
refdir = sys.argv[5]
plan = sys.argv[6]
os.environ["JAX_PROCESS_ID"] = str(rank)

import lightgbm_tpu as lgb
from lightgbm_tpu import telemetry
from lightgbm_tpu.resilience.faults import TrainingKilled

rng = np.random.default_rng(23)
n, nf = 2400, 6
X = rng.normal(size=(n, nf))
y = (X[:, 1] + 0.5 * X[:, 4] + rng.normal(size=n) * 0.3 > 0).astype(float)

params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "num_machines": 2,
          "machines": "127.0.0.1:%%s,127.0.0.1:0" %% port,
          "min_data_in_leaf": 5, "tree_learner": "data",
          "bagging_fraction": 0.8, "bagging_freq": 2,
          "snapshot_freq": 3, "tpu_collective_backoff": 0.0,
          "tpu_collective_soft_timeout": 0.05,
          "tpu_telemetry": "timers"}

def digest(b):
    return [round(float(v), 10) for v in b.predict(X[:300], raw_score=True)]

# (a) uninterrupted world=2 reference (its own snapshot stream)
pa = dict(params, checkpoint_dir=refdir)
ref_b = lgb.train(pa, lgb.Dataset(X, y), 9, verbose_eval=False)
ref = digest(ref_b)

# (b) the chaos schedule: a straggler stall mid-run, then the scheduled
# death — both ranks die at the same iteration boundary
telemetry.enable("timers"); telemetry.reset()
pb = dict(params, checkpoint_dir=ckdir, tpu_fault_plan=plan)
killed = False
try:
    lgb.train(pb, lgb.Dataset(X, y), 9, verbose_eval=False)
except TrainingKilled:
    killed = True
counts = telemetry.events.counts_snapshot()
stalls = counts.get("collective::stall", 0)
telemetry.reset(); telemetry.disable()

with open(out, "w") as fh:
    json.dump({"rank": rank, "killed": killed, "ref": ref,
               "stalls": stalls,
               "model_ref": ref_b.model_to_string(num_iteration=-1)}, fh)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.mark.slow
def test_two_process_chaos_elastic_resume(tmp_path):
    """The full elastic chaos story: a REAL two-process world=2 run hits
    a seed-determined schedule (straggler stall on a guarded DCN
    collective, then death at an iteration boundary), leaving rank-
    tagged shards + the mesh manifest + per-rank flight dumps; the
    parent process then resumes the run ELASTICALLY on world=1 and must
    reproduce the uninterrupted world=2 model."""
    sched = chaos_schedule(7)
    plan = "kill@iter=6,stall@round=%d;secs=1" % sched["stall_round"]
    port = _free_port()
    script = tmp_path / "chaos_worker.py"
    script.write_text(CHAOS_WORKER % {"repo": REPO})
    ckdir = str(tmp_path / "chaos_ck")
    refdir = str(tmp_path / "chaos_ref")
    os.makedirs(ckdir)
    os.makedirs(refdir)
    outs = [str(tmp_path / ("cw%d.json" % r)) for r in range(2)]
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(script), str(r), str(port), outs[r],
             ckdir, refdir, plan],
            env=env, cwd=str(tmp_path),   # fault-plan flight dumps
            # with no checkpoint_dir land in the worker's cwd — keep
            # that litter in tmp, not the repo root
            stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    for p in procs:
        try:
            _, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("chaos worker timed out")
        assert p.returncode == 0, err.decode()[-2000:]
    r0 = json.load(open(outs[0]))
    r1 = json.load(open(outs[1]))
    assert r0["killed"] and r1["killed"]
    assert r0["ref"] == r1["ref"]
    # the straggler stall was observed by the soft watchdog on each rank
    assert r0["stalls"] >= 1 and r1["stalls"] >= 1, (r0["stalls"],
                                                     r1["stalls"])
    # the dead mesh left both rank streams, the manifest, and postmortems
    ranks = {n.split(".r")[1] for n in os.listdir(ckdir)
             if n.endswith(".lgc")}
    assert ranks == {"0.lgc", "1.lgc"}
    from lightgbm_tpu.resilience import reshard
    man = reshard.load_manifest(ckdir)
    assert man is not None and man["world"] == 2
    assert os.path.exists(os.path.join(ckdir, "flight.r0.json"))
    assert os.path.exists(os.path.join(ckdir, "flight.r1.json"))

    # elastic resume IN THIS PROCESS on world=1: same params minus the
    # mesh (num_machines/machines are resume-volatile by design)
    rng = np.random.default_rng(23)
    n, nf = 2400, 6
    X = rng.normal(size=(n, nf))
    y = (X[:, 1] + 0.5 * X[:, 4]
         + rng.normal(size=n) * 0.3 > 0).astype(float)
    rp = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "min_data_in_leaf": 5, "tree_learner": "data",
          "bagging_fraction": 0.8, "bagging_freq": 2,
          "snapshot_freq": 3, "tpu_collective_backoff": 0.0,
          "tpu_collective_soft_timeout": 0.05,
          "checkpoint_dir": ckdir}
    res = lgb.train(rp, lgb.Dataset(X, y), 9, verbose_eval=False)
    assert res.num_trees() == 9
    assert reshard.load_manifest(ckdir)["world"] == 1
    got = [round(float(v), 10) for v in res.predict(X[:300],
                                                    raw_score=True)]
    assert got == r0["ref"], "elastic world=2 -> world=1 resume diverged"
