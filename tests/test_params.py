"""Formerly-dead parameters: extra_trees, feature_fraction_bynode, CEGB,
refit, pred_early_stop — each works (or errors loudly) per the reference
semantics it mirrors."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.utils.log import LightGBMError


def _data(n=1500, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = X[:, 0] + 0.5 * X[:, 1] - 0.3 * X[:, 2] + 0.1 * rng.normal(size=n)
    return X, y


def _trees_of(bst):
    bst._booster._materialize_pending()
    return bst._booster.models


def test_extra_trees_changes_model_and_is_seeded():
    X, y = _data()
    base = {"objective": "regression", "num_leaves": 15, "verbosity": -1}
    b0 = lgb.train(dict(base), lgb.Dataset(X, y), 5, verbose_eval=False)
    b1 = lgb.train({**base, "extra_trees": True}, lgb.Dataset(X, y), 5,
                   verbose_eval=False)
    b2 = lgb.train({**base, "extra_trees": True}, lgb.Dataset(X, y), 5,
                   verbose_eval=False)
    t0, t1, t2 = _trees_of(b0), _trees_of(b1), _trees_of(b2)
    # random thresholds differ from the exhaustive scan...
    assert not np.array_equal(t0[0].threshold, t1[0].threshold)
    # ...but are deterministic under the same extra_seed
    for a, b in zip(t1, t2):
        np.testing.assert_array_equal(a.threshold, b.threshold)
    # and still learn something
    r2 = 1 - np.var(y - b1.predict(X)) / np.var(y)
    assert r2 > 0.5


def test_feature_fraction_bynode():
    X, y = _data()
    base = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
            "feature_fraction_bynode": 0.5}
    b = lgb.train(base, lgb.Dataset(X, y), 5, verbose_eval=False)
    # per-node sampling: every feature should still appear somewhere
    used = set()
    for t in _trees_of(b):
        used.update(t.split_feature[:t.num_leaves - 1].tolist())
    assert len(used) > 3
    r2 = 1 - np.var(y - b.predict(X)) / np.var(y)
    assert r2 > 0.5


def test_cegb_split_penalty_prunes():
    X, y = _data()
    base = {"objective": "regression", "num_leaves": 63, "verbosity": -1,
            "min_gain_to_split": 0.0}
    b0 = lgb.train(dict(base), lgb.Dataset(X, y), 3, verbose_eval=False)
    b1 = lgb.train({**base, "cegb_penalty_split": 0.05},
                   lgb.Dataset(X, y), 3, verbose_eval=False)
    n0 = sum(t.num_leaves for t in _trees_of(b0))
    n1 = sum(t.num_leaves for t in _trees_of(b1))
    assert n1 < n0  # splitting now costs tradeoff*penalty*count


def test_cegb_coupled_penalty_limits_features():
    X, y = _data(f=8)
    pen = [10.0] * 8  # high cost to introduce each new feature
    base = {"objective": "regression", "num_leaves": 31, "verbosity": -1}
    b0 = lgb.train(dict(base), lgb.Dataset(X, y), 3, verbose_eval=False)
    b1 = lgb.train({**base, "cegb_tradeoff": 1.0,
                    "cegb_penalty_feature_coupled": pen},
                   lgb.Dataset(X, y), 3, verbose_eval=False)
    used0 = set()
    for t in _trees_of(b0):
        used0.update(t.split_feature[:t.num_leaves - 1].tolist())
    used1 = set()
    for t in _trees_of(b1):
        used1.update(t.split_feature[:t.num_leaves - 1].tolist())
    assert len(used1) <= len(used0)


def _cegb_lazy_data(tmp_path):
    """Deterministic binary problem round-tripped through CSV the way the
    reference golden below was generated (values %.9g-rounded)."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(2000, 8))
    y = (X[:, 0] + 0.5 * X[:, 1]
         + 0.2 * rng.normal(size=2000) > 0).astype(float)
    path = str(tmp_path / "cegb_train.csv")
    np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt="%.9g")
    return path


# Per-iteration training logloss of the REAL LightGBM binary (built from
# /root/reference) on the dataset above with the params below — pins the
# lazy on-demand penalty semantics (CalculateOndemandCosts + the
# feature_used_in_data_ bitset, cost_effective_gradient_boosting.hpp:47-114).
CEGB_LAZY_GOLDEN = [
    0.616674, 0.553374, 0.501297, 0.456948, 0.418483, 0.385537, 0.357251,
    0.332104, 0.309686, 0.289829, 0.272757, 0.256913, 0.24323, 0.230598,
    0.219254, 0.209052, 0.199477, 0.191094, 0.18345, 0.17599]


def test_cegb_lazy_reference_parity(tmp_path):
    path = _cegb_lazy_data(tmp_path)
    params = {"objective": "binary", "num_leaves": 15,
              "min_data_in_leaf": 20, "learning_rate": 0.1,
              "metric": "binary_logloss", "verbosity": -1,
              "label_column": 0, "header": False,
              "cegb_penalty_feature_lazy": [0.001] * 8,
              "cegb_tradeoff": 1.0}
    ds = lgb.Dataset(path, params=dict(params))
    evals = {}
    lgb.train(params, ds, num_boost_round=20, valid_sets=[ds],
              valid_names=["training"],
              callbacks=[lgb.record_evaluation(evals)], verbose_eval=False)
    ours = evals["training"]["binary_logloss"]
    for it, (got, ref) in enumerate(zip(ours, CEGB_LAZY_GOLDEN), 1):
        assert abs(got - ref) <= 1e-3 * abs(ref) + 1e-6, (
            "iteration %d: ours=%.6f ref=%.6f" % (it, got, ref))


@pytest.mark.slow  # tier-1 870s budget: cheaper sibling tests cover this area
def test_cegb_lazy_zero_matches_coupled_zero():
    # a zero lazy penalty vector must reproduce the zero-coupled CEGB
    # model exactly (identical gain path, bitset contributes nothing)
    X, y = _data(f=8)
    base = {"objective": "regression", "num_leaves": 31, "verbosity": -1}
    bz = lgb.train({**base, "cegb_penalty_feature_lazy": [0.0] * 8},
                   lgb.Dataset(X, y), 5, verbose_eval=False)
    bc = lgb.train({**base, "cegb_penalty_feature_coupled": [0.0] * 8},
                   lgb.Dataset(X, y), 5, verbose_eval=False)
    np.testing.assert_allclose(bz.predict(X), bc.predict(X), atol=1e-12)


@pytest.mark.slow  # tier-1 870s budget: cheaper sibling tests cover this area
def test_cegb_lazy_heavy_penalty_suppresses_splits():
    # a per-row acquisition cost far above any gain: no split clears it
    X, y = _data(n=500, f=8)
    b = lgb.train({"objective": "regression", "verbosity": -1,
                   "num_leaves": 15,
                   "cegb_penalty_feature_lazy": [1e6] * 8},
                  lgb.Dataset(X, y), 3, verbose_eval=False)
    assert all(t.num_leaves == 1 for t in _trees_of(b))


def test_cegb_lazy_parallel_raises():
    X, y = _data(n=300)
    with pytest.raises(LightGBMError):
        lgb.train({"objective": "regression", "verbosity": -1,
                   "tree_learner": "data",
                   "cegb_penalty_feature_lazy": [1.0] * 8},
                  lgb.Dataset(X, y), 1, verbose_eval=False)


def test_forcedsplits_missing_file_raises():
    # forced splits are implemented (tests/test_forced_splits.py); a
    # nonexistent spec file must still fail loudly, not silently no-op
    X, y = _data(n=300)
    with pytest.raises((LightGBMError, OSError)):
        lgb.train({"objective": "regression", "verbosity": -1,
                   "forcedsplits_filename": "foo.json"},
                  lgb.Dataset(X, y), 1, verbose_eval=False)


def test_refit_keeps_structure_updates_leaves():
    X, y = _data(seed=1)
    X2, y2 = _data(seed=2)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(X, y), 10,
                    verbose_eval=False)
    new = bst.refit(X2, y2, decay_rate=0.5)
    t_old, t_new = _trees_of(bst), _trees_of(new)
    assert len(t_old) == len(t_new)
    for a, b in zip(t_old, t_new):
        np.testing.assert_array_equal(
            a.split_feature[:a.num_leaves - 1],
            b.split_feature[:b.num_leaves - 1])       # same structure
    changed = any(
        not np.allclose(a.leaf_value[:a.num_leaves],
                        b.leaf_value[:b.num_leaves])
        for a, b in zip(t_old, t_new))
    assert changed                                     # new leaf values
    # refitted model fits the new data better than the old model does
    mse_old = np.mean((bst.predict(X2) - y2) ** 2)
    mse_new = np.mean((new.predict(X2) - y2) ** 2)
    assert mse_new < mse_old


def test_batched_scan_respects_changed_hyperparams():
    """Two trainings on the SAME Dataset with different regularization must
    differ (the fused-scan cache must not bake hyperparameters in)."""
    X, y = _data(n=1200)
    ds = lgb.Dataset(X, y)
    base = {"objective": "regression", "num_leaves": 31, "verbosity": -1}
    b1 = lgb.train(dict(base), ds, 8, verbose_eval=False)
    b2 = lgb.train({**base, "lambda_l2": 1000.0}, ds, 8, verbose_eval=False)
    p1, p2 = b1.predict(X), b2.predict(X)
    assert not np.allclose(p1, p2)
    assert np.abs(p2).mean() < np.abs(p1).mean()  # heavy L2 shrinks outputs


def test_batched_scan_respects_objective_hyperparams_and_new_labels():
    """The fused-scan cache must also honor (a) scalars baked into the
    objective's grad closure (scale_pos_weight) and (b) replaced dataset
    fields — both bypass the traced SplitParams."""
    rng = np.random.default_rng(11)
    X = rng.normal(size=(1500, 5))
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(float)
    base = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    ds = lgb.Dataset(X, y, free_raw_data=False)
    b1 = lgb.train(dict(base), ds, 20, verbose_eval=False)
    b2 = lgb.train({**base, "scale_pos_weight": 25.0}, ds, 20,
                   verbose_eval=False)
    p1, p2 = b1.predict(X), b2.predict(X)
    assert not np.allclose(p1, p2)
    assert p2.mean() > p1.mean()   # up-weighted positives push probs up
    # replaced labels on the SAME Dataset retrain against the new targets
    ds.set_label(1.0 - y)
    b3 = lgb.train(dict(base), ds, 20, verbose_eval=False)
    p3 = b3.predict(X)
    assert np.corrcoef(p1, p3)[0, 1] < -0.5


def test_bagging_not_silently_dropped():
    """bagging_fraction < 1 must keep bagging active every iteration (the
    fused batch path must not engage and train full-data)."""
    X, y = _data(n=3000)
    base = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
            "bagging_freq": 1, "bagging_seed": 7}
    b_full = lgb.train(dict(base), lgb.Dataset(X, y), 6, verbose_eval=False)
    b_bag = lgb.train({**base, "bagging_fraction": 0.5},
                      lgb.Dataset(X, y), 6, verbose_eval=False)
    t_full, t_bag = _trees_of(b_full), _trees_of(b_bag)
    # bagged trees must see ~half the rows at their roots, every iteration
    for t in t_bag[1:]:
        assert t.internal_count[0] < 0.7 * X.shape[0]
    for t in t_full[1:]:
        assert t.internal_count[0] == X.shape[0]


# -- reset_parameter / ResetConfig (gbdt.cpp:704) -------------------------

def test_reset_parameter_learning_rate_schedule():
    X, y = _data()
    ds = lgb.Dataset(X, y)
    base = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
            "learning_rate": 0.3}
    # decaying schedule vs constant: both must train, schedules differ
    b0 = lgb.train(dict(base), ds, 10, verbose_eval=False)
    b1 = lgb.train(dict(base), lgb.Dataset(X, y), 10, verbose_eval=False,
                   callbacks=[lgb.reset_parameter(
                       learning_rate=[0.3 * (0.9 ** i) for i in range(10)])])
    assert np.abs(b0.predict(X) - b1.predict(X)).max() > 1e-8


def test_reset_parameter_num_leaves_schedule():
    # static grower knob: later trees must respect the smaller cap
    X, y = _data()
    b = lgb.train({"objective": "regression", "num_leaves": 31,
                   "verbosity": -1}, lgb.Dataset(X, y), 6,
                  verbose_eval=False,
                  callbacks=[lgb.reset_parameter(
                      num_leaves=[31, 31, 31, 4, 4, 4])])
    trees = _trees_of(b)
    assert max(t.num_leaves for t in trees[:3]) > 4
    assert all(t.num_leaves <= 4 for t in trees[3:])


def test_reset_parameter_bagging_schedule():
    # bagging switched ON mid-training: later trees see fewer in-bag rows
    X, y = _data(n=2000)
    b = lgb.train({"objective": "regression", "num_leaves": 15,
                   "verbosity": -1, "bagging_seed": 7},
                  lgb.Dataset(X, y), 6, verbose_eval=False,
                  callbacks=[lgb.reset_parameter(
                      bagging_fraction=[1.0, 1.0, 1.0, 0.5, 0.5, 0.5],
                      bagging_freq=[0, 0, 0, 1, 1, 1])])
    trees = _trees_of(b)
    counts = [int(t.leaf_count[:t.num_leaves].sum()) for t in trees]
    assert counts[0] == 2000 and counts[1] == 2000 and counts[2] == 2000
    assert all(800 < c < 1200 for c in counts[3:])


def test_reset_parameter_bagging_masks_differ_across_iterations():
    # a CONSTANT bagging schedule must not reseed the bag RNG every
    # iteration (that would redraw the identical mask each time)
    X, y = _data(n=2000)
    masks = []

    class _Spy:
        order = 99
        before_iteration = False

        def __call__(self, env):
            masks.append(np.asarray(env.model._booster._bag_mask_dev))

    lgb.train({"objective": "regression", "num_leaves": 15,
               "verbosity": -1}, lgb.Dataset(X, y), 4, verbose_eval=False,
              callbacks=[lgb.reset_parameter(bagging_fraction=[0.5] * 4,
                                             bagging_freq=[1] * 4),
                         _Spy()])
    assert len(masks) == 4
    assert any(not np.array_equal(masks[0], m) for m in masks[1:])


def test_reset_parameter_constant_schedule_is_noop():
    # scheduling the param at its constant value must not change the model
    X, y = _data()
    base = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
            "lambda_l2": 0.5}
    b0 = lgb.train(dict(base), lgb.Dataset(X, y), 8, verbose_eval=False)
    b1 = lgb.train(dict(base), lgb.Dataset(X, y), 8, verbose_eval=False,
                   callbacks=[lgb.reset_parameter(lambda_l2=[0.5] * 8)])
    np.testing.assert_allclose(b0.predict(X), b1.predict(X), atol=1e-12)


def test_reset_parameter_fixed_key_warns_not_crashes():
    X, y = _data()
    b = lgb.train({"objective": "regression", "num_leaves": 15,
                   "verbosity": -1}, lgb.Dataset(X, y), 3,
                  verbose_eval=False,
                  callbacks=[lgb.reset_parameter(max_bin=[64, 64, 64])])
    assert len(_trees_of(b)) == 3   # trained through, key ignored loudly


def test_booster_reset_parameter_api():
    X, y = _data()
    b = lgb.train({"objective": "regression", "num_leaves": 15,
                   "verbosity": -1}, lgb.Dataset(X, y), 3,
                  verbose_eval=False)
    b._booster  # Booster facade wraps the inner GBDT
    b.reset_parameter({"learning_rate": 0.01})
    assert b._booster.shrinkage_rate == 0.01


def test_reset_parameter_mixed_schedule_bagging_still_varies():
    # a changing lr + CONSTANT bagging keys: the constant keys must not
    # be re-applied (re-seeding the bag RNG) just because lr changed
    X, y = _data(n=2000)
    masks = []

    class _Spy:
        order = 99
        before_iteration = False

        def __call__(self, env):
            masks.append(np.asarray(env.model._booster._bag_mask_dev))

    lgb.train({"objective": "regression", "num_leaves": 15,
               "verbosity": -1}, lgb.Dataset(X, y), 4, verbose_eval=False,
              callbacks=[lgb.reset_parameter(
                  learning_rate=[0.3 * 0.9 ** i for i in range(4)],
                  bagging_fraction=[0.5] * 4, bagging_freq=[1] * 4),
                  _Spy()])
    assert any(not np.array_equal(masks[0], m) for m in masks[1:])


def test_reset_parameter_on_loaded_model():
    # prediction-only booster (no training state): config-level updates
    # apply, nothing crashes (LGBM_BoosterResetParameter contract)
    X, y = _data(n=500)
    b = lgb.train({"objective": "regression", "num_leaves": 15,
                   "verbosity": -1}, lgb.Dataset(X, y), 3,
                  verbose_eval=False)
    loaded = lgb.Booster(model_str=b.model_to_string())
    loaded.reset_parameter({"learning_rate": 0.05, "bagging_fraction": 0.5})
    assert loaded._booster.shrinkage_rate == 0.05
    np.testing.assert_allclose(loaded.predict(X), b.predict(X), atol=1e-12)
