"""Telemetry subsystem: no-op-when-off guarantees, span registry semantics,
Chrome-trace export round-trip, and the TrainingMonitor riding the
CallbackEnv protocol without altering it."""
import json
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import telemetry
from lightgbm_tpu.telemetry import events
from lightgbm_tpu.utils import timer


@pytest.fixture(autouse=True)
def _telemetry_clean():
    """Every test starts and ends with telemetry OFF and an empty registry
    (telemetry state is process-global by design)."""
    events.disable()
    events.reset()
    events.set_out_path(None)
    yield
    events.disable()
    events.reset()
    events.set_out_path(None)


def _toy(n=400, f=8, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(float)
    return X, y


TOY_PARAMS = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
              "verbosity": -1, "metric": "none"}


# ---------------------------------------------------------------------------
# disabled-by-default guarantees (tier-1 guard)
# ---------------------------------------------------------------------------

def test_disabled_by_default_noop():
    assert events.mode() == events.OFF
    assert not events.enabled() and not timer.enabled()
    with events.scope("x", category="misc"):
        pass
    events.add("y", 1.0)
    events.count("z")
    events.record_iteration({"iteration": 0})
    assert events.snapshot() == {}
    assert events.counts_snapshot() == {}
    assert events.events_snapshot() == []
    assert events.iteration_records() == []
    # device_wait must NOT block (and must hand the value back) when off
    sentinel = object()
    assert events.device_wait("w", sentinel) is sentinel


def test_atexit_hook_silent_when_disabled(capsys):
    events._report_at_exit()
    telemetry.print_report()
    out = capsys.readouterr()
    assert out.out == "" and out.err == ""


def test_configure_off_is_noop():
    events.configure("off", None)
    assert events.mode() == events.OFF
    cfg = lgb.Config({"tpu_telemetry": "off"})
    events.configure_from_config(cfg)
    assert events.mode() == events.OFF


def test_config_telemetry_does_not_leak_across_trains(tmp_path):
    """tpu_telemetry= is scoped to the trains that ask for it: the next
    lgb.train with default params goes back to OFF, while an explicit
    enable() survives config-default trains."""
    X, y = _toy(n=300)
    out = str(tmp_path / "leak.json")
    lgb.train(dict(TOY_PARAMS, tpu_telemetry="trace", telemetry_out=out),
              lgb.Dataset(X, y), 2, verbose_eval=False)
    assert events.mode() == events.TRACE
    lgb.train(dict(TOY_PARAMS), lgb.Dataset(X, y), 2, verbose_eval=False)
    assert events.mode() == events.OFF
    events.enable("timers")
    lgb.train(dict(TOY_PARAMS), lgb.Dataset(X, y), 2, verbose_eval=False)
    assert events.mode() == events.TIMERS


def test_noop_scope_overhead_is_tiny():
    """The disabled path is one int compare + generator setup; a coarse
    ceiling guards against someone adding real work to it."""
    t0 = time.perf_counter()
    for _ in range(20_000):
        with events.scope("hot"):
            pass
    elapsed = time.perf_counter() - t0
    assert elapsed < 1.0, "no-op scope path cost %.3fs / 20k calls" % elapsed


def test_training_off_records_nothing_1k_rows():
    """tpu_telemetry=off (default): a 1k-row run leaves the registry empty
    and a warm re-run stays fast (coarse per-iteration overhead guard)."""
    X, y = _toy(n=1000)
    ds = lgb.Dataset(X, y)
    lgb.train(dict(TOY_PARAMS), ds, 8, verbose_eval=False)
    assert events.snapshot() == {}
    assert events.events_snapshot() == []
    t0 = time.perf_counter()
    ds2 = lgb.Dataset(X, y)
    bst = lgb.train(dict(TOY_PARAMS), ds2, 8, verbose_eval=False)
    bst._booster._materialize_pending()
    warm = time.perf_counter() - t0
    assert events.snapshot() == {}
    assert warm < 30.0, "warm 1k-row 8-iter run took %.1fs" % warm


def test_off_vs_timers_identical_model():
    """Enabling telemetry must not change the trained model."""
    X, y = _toy(n=600)
    bst_off = lgb.train(dict(TOY_PARAMS), lgb.Dataset(X, y), 6,
                        verbose_eval=False)
    p_off = bst_off.predict(X)
    events.enable("timers")
    bst_on = lgb.train(dict(TOY_PARAMS), lgb.Dataset(X, y), 6,
                       verbose_eval=False)
    p_on = bst_on.predict(X)
    np.testing.assert_array_equal(p_off, p_on)
    assert events.snapshot(), "timers mode recorded nothing"


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_span_nesting_and_trace_events():
    events.enable("trace")
    with events.scope("outer", category="a"):
        time.sleep(0.002)
        with events.scope("inner", category="b", tag=1):
            time.sleep(0.001)
    evs = events.events_snapshot()
    by_name = {e["name"]: e for e in evs}
    assert set(by_name) == {"outer", "inner"}
    inner, outer = by_name["inner"], by_name["outer"]
    assert inner["parent"] == "outer" and "parent" not in outer
    assert inner["args"] == {"tag": 1}
    # inner nests inside outer on the timeline
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-6
    snap = events.snapshot()
    assert snap["outer"][1] == 1 and snap["inner"][1] == 1
    assert events.snapshot_full()["inner"][2] == "b"


def test_thread_safety():
    events.enable("timers")
    threads, per = 8, 200
    barrier = threading.Barrier(threads)

    def work(i):
        barrier.wait()
        for _ in range(per):
            with events.scope("shared"):
                pass
            with events.scope("own-%d" % i):
                pass
            events.count("hits")

    ts = [threading.Thread(target=work, args=(i,)) for i in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    snap = events.snapshot()
    assert snap["shared"][1] == threads * per
    for i in range(threads):
        assert snap["own-%d" % i][1] == per
    assert events.counts_snapshot()["hits"] == threads * per


def test_timer_module_aliases():
    """utils.timer keeps its original surface as thin telemetry aliases."""
    timer.enable()
    assert events.mode() == events.TIMERS and timer.enabled()

    @timer.timed("alias::fn")
    def fn():
        return 42

    assert fn() == 42
    with timer.scope("alias::scope"):
        pass
    timer.add("alias::manual", 0.5)
    snap = timer.snapshot()
    assert snap["alias::fn"][1] == 1
    assert snap["alias::scope"][1] == 1
    assert snap["alias::manual"] == (0.5, 1)
    timer.disable()
    assert not timer.enabled()


def test_print_report_format(capsys):
    events.enable("timers")
    events.add("scope::a", 2.0, category="boosting")
    events.add("scope::b", 1.0)
    telemetry.print_report()
    err = capsys.readouterr().err
    assert "time-tag report" in err
    assert "scope::a" in err and "scope::b" in err and "(sum)" in err
    # sorted by total seconds, largest first
    assert err.index("scope::a") < err.index("scope::b")


# ---------------------------------------------------------------------------
# Chrome-trace export round-trip on a real (tiny) training run
# ---------------------------------------------------------------------------

def test_chrome_trace_roundtrip(tmp_path):
    out = str(tmp_path / "run.json")
    X, y = _toy(n=500)
    ds = lgb.Dataset(X, y)
    params = dict(TOY_PARAMS, tpu_telemetry="trace", telemetry_out=out)
    bst = lgb.train(params, ds, 4, verbose_eval=False)
    assert bst.num_trees() == 4
    trace = json.loads((tmp_path / "run.json").read_text())
    evs = trace["traceEvents"]
    assert evs, "trace has no events"
    for e in evs:
        assert {"name", "cat", "ph", "ts", "dur", "pid", "tid"} <= set(e)
        assert e["ph"] == "X"
    cats = {e["cat"] for e in evs}
    assert {"boosting", "tree_learner", "ops"} <= cats
    names = {e["name"] for e in evs}
    assert "boosting::TrainOneIter" in names
    assert "tree_learner::Train(launch)" in names
    assert any(n.startswith("ops::grow_tree") for n in names)
    # metrics snapshot JSONL next to the trace
    lines = [json.loads(ln) for ln in
             (tmp_path / "run.metrics.jsonl").read_text().splitlines()]
    kinds = {ln["kind"] for ln in lines}
    assert {"header", "timer", "iteration"} <= kinds
    iters = [ln for ln in lines if ln["kind"] == "iteration"]
    assert len(iters) == 4


@pytest.mark.slow  # tier-1 870s budget: cheaper sibling tests cover this area
def test_collective_category_on_mesh(tmp_path):
    """Sharded (data-parallel) training tags its dispatches 'collective'."""
    out = str(tmp_path / "mesh.json")
    X, y = _toy(n=512)
    ds = lgb.Dataset(X, y)
    params = dict(TOY_PARAMS, tree_learner="data", tpu_telemetry="trace",
                  telemetry_out=out)
    bst = lgb.train(params, ds, 3, verbose_eval=False)
    assert bst.num_trees() == 3
    trace = json.loads((tmp_path / "mesh.json").read_text())
    cats = {e["cat"] for e in trace["traceEvents"]}
    assert "collective" in cats
    coll = [e for e in trace["traceEvents"] if e["cat"] == "collective"]
    assert any(e["name"].startswith("collective::") for e in coll)
    assert all(e["args"]["shards"] >= 1 for e in coll
               if "args" in e and "shards" in e["args"])


# ---------------------------------------------------------------------------
# TrainingMonitor through the CallbackEnv protocol
# ---------------------------------------------------------------------------

def test_training_monitor_with_callback_consumers():
    """The monitor rides as one more post-iteration callback: per-iteration
    records exist AND print_evaluation/record_evaluation see the exact same
    CallbackEnv they always did."""
    X, y = _toy(n=500)
    Xv, yv = _toy(n=200, seed=11)
    ds = lgb.Dataset(X, y)
    vs = lgb.Dataset(Xv, yv, reference=ds)
    evals_result = {}
    rounds = 5
    params = dict(TOY_PARAMS, metric="binary_logloss",
                  tpu_telemetry="timers")
    bst = lgb.train(params, ds, rounds, valid_sets=[vs],
                    valid_names=["hold"], verbose_eval=2,
                    callbacks=[lgb.record_evaluation(evals_result)])
    # CallbackEnv contract untouched: record_evaluation populated normally
    assert list(evals_result) == ["hold"]
    assert len(evals_result["hold"]["binary_logloss"]) == rounds
    # monitor attached and recorded every iteration
    mon = bst._telemetry_monitor
    assert len(mon.records) == rounds
    for i, rec in enumerate(mon.records):
        assert rec["iteration"] == i
        assert rec["wall"] >= 0.0
        assert isinstance(rec["buckets"], dict)
        assert rec["num_evals"] >= 1
    # eval spans got bucketed, and the registry mirrors the records
    assert any("eval" in r["buckets"] or "boosting" in r["buckets"]
               for r in mon.records)
    assert len(events.iteration_records()) == rounds


def test_monitor_standalone_record():
    events.enable("timers")
    mon = telemetry.TrainingMonitor(name="unit")
    with events.scope("s", category="boosting"):
        time.sleep(0.001)
    rec = mon.record(0)
    assert rec["monitor"] == "unit" and rec["iteration"] == 0
    assert rec["buckets"].get("boosting", 0) > 0
    with events.scope("s", category="boosting"):
        time.sleep(0.001)
    rec2 = mon.record(1)
    assert rec2["wall"] > 0
    assert rec2["buckets"].get("boosting", 0) > 0


# ---------------------------------------------------------------------------
# xplane device profile (needs the TF proto bindings; CPU traces carry no
# XLA-op device planes, so this only checks the parse/report plumbing)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_xplane_parse_smoke(tmp_path):
    pytest.importorskip("tensorflow.tsl.profiler.protobuf")
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.telemetry import xplane
    tdir = str(tmp_path / "trace")
    with xplane.collect_trace(tdir):
        jax.block_until_ready(jax.jit(lambda x: x * 2)(jnp.ones(128)))
    planes = xplane.parse_xplane_dir(tdir)
    report = xplane.format_device_report(planes, iters=1)
    assert isinstance(report, str) and report
