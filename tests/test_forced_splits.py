"""forcedsplits_filename: forced JSON split trees applied before gain-driven
growth (SerialTreeLearner::ForceSplits, serial_tree_learner.cpp:411-521)."""
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=4000, seed=5):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 6))
    y = ((X[:, 0] > 0.3) ^ (X[:, 2] > -0.2)
         ).astype(float) + rng.normal(size=n) * 0.1
    return X, y


def _train(tmp_path, forced_spec, n_leaves=8, extra=None):
    X, y = _data()
    fname = os.path.join(str(tmp_path), "forced.json")
    with open(fname, "w") as fh:
        json.dump(forced_spec, fh)
    params = {"objective": "regression", "num_leaves": n_leaves,
              "verbosity": -1, "min_data_in_leaf": 5,
              "forcedsplits_filename": fname}
    if extra:
        params.update(extra)
    bst = lgb.train(params, lgb.Dataset(X, y), 3, verbose_eval=False)
    return bst, X, y


def test_forced_two_levels(tmp_path):
    """Root forced to feature 1, its left child forced to feature 3 —
    neither would be the gain-chosen split (the signal is in 0 and 2)."""
    spec = {"feature": 1, "threshold": 0.0,
            "left": {"feature": 3, "threshold": 0.5}}
    bst, X, y = _train(tmp_path, spec)
    model = bst.dump_model()
    if isinstance(model, str):
        model = json.loads(model)
    t0 = model["tree_info"][0]["tree_structure"]
    assert t0["split_feature"] == 1
    assert abs(t0["threshold"] - 0.0) < 0.2   # bin upper bound near 0.0
    left = t0["left_child"]
    assert left["split_feature"] == 3
    # right subtree continues with gain-driven splits on the real signal
    feats = set()

    def walk(node):
        if "split_feature" in node:
            feats.add(node["split_feature"])
            walk(node["left_child"])
            walk(node["right_child"])
    walk(t0)
    assert {0, 2} & feats, "gain-driven splits should follow the forced ones"


def test_forced_right_child(tmp_path):
    spec = {"feature": 1, "threshold": 0.0,
            "right": {"feature": 4, "threshold": -0.3}}
    bst, X, y = _train(tmp_path, spec)
    model = bst.dump_model()
    if isinstance(model, str):
        model = json.loads(model)
    t0 = model["tree_info"][0]["tree_structure"]
    assert t0["split_feature"] == 1
    assert t0["right_child"]["split_feature"] == 4


def test_forced_predictions_consistent(tmp_path):
    """Forced models still predict with host trees == device scores."""
    spec = {"feature": 5, "threshold": 0.1}
    bst, X, y = _train(tmp_path, spec)
    pred = bst.predict(X)
    assert np.isfinite(pred).all()
    # quality sanity: still learns something despite the forced root
    base = np.mean((y - y.mean()) ** 2)
    assert np.mean((y - pred) ** 2) < base


def test_forced_threshold_bin_goes_left(tmp_path):
    """The bin containing the forced threshold partitions LEFT and the saved
    model records the real threshold (DenseBin::Split sends
    bin <= ValueToBin(v) left; regression pin for an off-by-one that sent
    it right with a one-bin-low saved threshold)."""
    rng = np.random.default_rng(0)
    x = rng.choice([0.0, 1.0, 2.0], size=3000, p=[0.4, 0.35, 0.25])
    X = x[:, None]
    y = (x >= 1.0).astype(float) + rng.normal(size=3000) * 0.05
    fname = os.path.join(str(tmp_path), "forced.json")
    with open(fname, "w") as fh:
        json.dump({"feature": 0, "threshold": 1.5}, fh)
    params = {"objective": "regression", "num_leaves": 2,
              "verbosity": -1, "min_data_in_leaf": 5,
              "learning_rate": 1.0,
              "forcedsplits_filename": fname}
    bst = lgb.train(params, lgb.Dataset(X, y), 1, verbose_eval=False)
    model = bst.dump_model()
    if isinstance(model, str):
        model = json.loads(model)
    t0 = model["tree_info"][0]["tree_structure"]
    assert t0["split_feature"] == 0
    assert abs(t0["threshold"] - 1.5) < 1e-9
    n_left_expected = int(np.sum(x <= 1.0))
    assert t0["left_child"]["leaf_count"] == n_left_expected
    assert t0["right_child"]["leaf_count"] == 3000 - n_left_expected
    # prediction agrees with the partition
    pred = bst.predict(np.array([[0.0], [1.0], [2.0]]))
    assert abs(pred[0] - pred[1]) < 1e-9
    assert abs(pred[1] - pred[2]) > 0.1


def test_no_force_file_unchanged():
    X, y = _data()
    params = {"objective": "regression", "num_leaves": 8,
              "verbosity": -1, "min_data_in_leaf": 5}
    bst = lgb.train(dict(params), lgb.Dataset(X, y), 2, verbose_eval=False)
    model = bst.dump_model()
    if isinstance(model, str):
        model = json.loads(model)
    assert model["tree_info"][0]["tree_structure"]["split_feature"] in (0, 2)
