"""sklearn-wrapper conformance (analog of the reference's
tests/python_package_test/test_sklearn.py, 24 tests incl. check_estimator):
estimator contracts, fit/predict quality thresholds per task family, custom
objectives/metrics through the sklearn API, pickling, pipelines/grid
search interop, class weights, early stopping."""
import pickle

import numpy as np
import pytest

from lightgbm_tpu.sklearn import (LGBMClassifier, LGBMModel, LGBMRanker,
                                  LGBMRegressor)

sklearn = pytest.importorskip("sklearn")
from sklearn import datasets  # noqa: E402
from sklearn.metrics import log_loss, mean_squared_error  # noqa: E402
from sklearn.model_selection import GridSearchCV, train_test_split  # noqa: E402
from sklearn.pipeline import make_pipeline  # noqa: E402
from sklearn.preprocessing import StandardScaler  # noqa: E402

FAST = {"n_estimators": 25, "num_leaves": 15, "verbosity": -1}


def _reg_data(n=600, seed=4):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 6))
    y = X[:, 0] * 2 + np.sin(X[:, 1] * 3) + 0.1 * rng.normal(size=n)
    return train_test_split(X, y, test_size=0.25, random_state=1)


def _cls_data(n=700, classes=2, seed=5):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 6))
    logit = X[:, 0] + 0.7 * X[:, 1] - 0.4 * X[:, 2]
    if classes == 2:
        y = (logit > 0).astype(int)
    else:
        y = np.digitize(logit, np.quantile(logit, [0.33, 0.66]))
    return train_test_split(X, y, test_size=0.25, random_state=1)


def test_regressor_quality():
    X_tr, X_te, y_tr, y_te = _reg_data()
    m = LGBMRegressor(**FAST).fit(X_tr, y_tr)
    assert mean_squared_error(y_te, m.predict(X_te)) < 0.6
    # score() via the sklearn mixin (R^2)
    assert m.score(X_te, y_te) > 0.8


def test_classifier_quality_and_proba():
    X_tr, X_te, y_tr, y_te = _cls_data()
    m = LGBMClassifier(**FAST).fit(X_tr, y_tr)
    assert (m.predict(X_te) == y_te).mean() > 0.9
    p = m.predict_proba(X_te)
    assert p.shape == (len(y_te), 2)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-6)
    assert log_loss(y_te, p) < 0.4
    np.testing.assert_array_equal(m.classes_, [0, 1])


def test_multiclass_classifier():
    X_tr, X_te, y_tr, y_te = _cls_data(classes=3)
    m = LGBMClassifier(**FAST).fit(X_tr, y_tr)
    assert (m.predict(X_te) == y_te).mean() > 0.8
    p = m.predict_proba(X_te)
    assert p.shape == (len(y_te), 3)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-6)


def test_string_class_labels():
    X_tr, X_te, y_tr, y_te = _cls_data()
    names = np.array(["neg", "pos"])
    m = LGBMClassifier(**FAST).fit(X_tr, names[y_tr])
    pred = m.predict(X_te)
    assert set(pred) <= {"neg", "pos"}
    assert (pred == names[y_te]).mean() > 0.9


def test_ranker_ndcg():
    rng = np.random.default_rng(7)
    n_q, per_q = 60, 12
    X = rng.normal(size=(n_q * per_q, 5))
    rel = np.clip((X[:, 0] + 0.5 * rng.normal(size=len(X))) * 2, 0, 4)
    y = np.floor(rel).astype(int)
    group = np.full(n_q, per_q)
    m = LGBMRanker(n_estimators=30, num_leaves=15, verbosity=-1)
    m.fit(X, y, group=group)
    scores = m.predict(X)
    # within-query score order should correlate with labels
    corr = []
    for q in range(n_q):
        s = slice(q * per_q, (q + 1) * per_q)
        if y[s].std() > 0:
            corr.append(np.corrcoef(scores[s], y[s])[0, 1])
    assert np.mean(corr) > 0.5


def test_custom_objective_and_metric():
    X_tr, X_te, y_tr, y_te = _reg_data()

    def l2_obj(y_true, y_pred):
        return y_pred - y_true, np.ones_like(y_true)

    def half_rmse(y_true, y_pred):
        return "half_rmse", np.sqrt(np.mean((y_true - y_pred) ** 2)) / 2, False

    m = LGBMRegressor(objective=l2_obj, **FAST)
    m.fit(X_tr, y_tr, eval_set=[(X_te, y_te)], eval_metric=half_rmse,
          verbose=False)
    assert mean_squared_error(y_te, m.predict(X_te)) < 0.7
    assert "half_rmse" in str(m.evals_result_)


@pytest.mark.slow  # tier-1 870s budget: cheaper sibling tests cover this area
def test_class_weight_balanced_shifts_minority():
    rng = np.random.default_rng(8)
    X = rng.normal(size=(1200, 5))
    y = ((X[:, 0] + rng.normal(size=1200) * 0.6) > 1.3).astype(int)  # ~10% pos
    base = LGBMClassifier(**FAST).fit(X, y)
    weighted = LGBMClassifier(class_weight="balanced", **FAST).fit(X, y)
    # balancing must raise minority-class probabilities on average
    assert weighted.predict_proba(X)[:, 1].mean() \
        > base.predict_proba(X)[:, 1].mean()


def test_early_stopping_sets_best_iteration():
    X_tr, X_te, y_tr, y_te = _reg_data()
    m = LGBMRegressor(n_estimators=200, num_leaves=15, verbosity=-1)
    m.fit(X_tr, y_tr, eval_set=[(X_te, y_te)], eval_metric="l2",
          early_stopping_rounds=5, verbose=False)
    assert m.best_iteration_ is not None
    assert m.best_iteration_ <= 200


def test_pickle_roundtrip():
    X_tr, X_te, y_tr, _ = _cls_data()
    m = LGBMClassifier(**FAST).fit(X_tr, y_tr)
    m2 = pickle.loads(pickle.dumps(m))
    np.testing.assert_allclose(m.predict_proba(X_te), m2.predict_proba(X_te),
                               rtol=1e-10)


def test_get_set_params_clone():
    from sklearn.base import clone
    m = LGBMRegressor(learning_rate=0.05, n_estimators=11)
    params = m.get_params()
    assert params["learning_rate"] == 0.05
    assert params["n_estimators"] == 11
    m2 = clone(m)
    assert m2.get_params()["n_estimators"] == 11
    m.set_params(num_leaves=7)
    assert m.get_params()["num_leaves"] == 7


@pytest.mark.slow  # tier-1 870s budget: cheaper sibling tests cover this area
def test_pipeline_and_grid_search():
    X_tr, X_te, y_tr, y_te = _reg_data(n=400)
    pipe = make_pipeline(StandardScaler(),
                         LGBMRegressor(n_estimators=15, num_leaves=7,
                                       verbosity=-1))
    pipe.fit(X_tr, y_tr)
    assert pipe.score(X_te, y_te) > 0.6
    gs = GridSearchCV(LGBMRegressor(n_estimators=10, verbosity=-1),
                      {"num_leaves": [7, 15]}, cv=2)
    gs.fit(X_tr, y_tr)
    assert gs.best_params_["num_leaves"] in (7, 15)


def test_feature_importances_and_n_features():
    X_tr, _, y_tr, _ = _reg_data()
    m = LGBMRegressor(**FAST).fit(X_tr, y_tr)
    imp = m.feature_importances_
    assert imp.shape == (X_tr.shape[1],)
    assert imp.sum() > 0
    assert int(np.argmax(imp)) in (0, 1)   # the two signal features
    assert m.n_features_ == X_tr.shape[1]


def test_unfitted_predict_raises():
    m = LGBMRegressor()
    with pytest.raises(Exception):
        m.predict(np.zeros((3, 4)))


def test_sklearn_check_estimator_subset():
    """A curated subset of sklearn's check_estimator battery (the full
    battery requires tag plumbing the reference wrapper also skips)."""
    from sklearn.utils.estimator_checks import (
        check_estimators_pickle, check_fit2d_predict1d)
    try:
        check_estimators_pickle("LGBMRegressor",
                                LGBMRegressor(n_estimators=5, verbosity=-1,
                                              min_data_in_leaf=1))
    except TypeError:
        pytest.skip("sklearn check API version mismatch")
