"""Bundle-native block scan (ops/pallas_scan.scan_blocks) equivalence.

The block kernel scans [G, 256] group planes directly; the established
per-feature kernel (scan_pair) scans one row per feature, each holding a
copy of its group block with window-offset masks — the layout the persist
grower used before the bundle-native path. Given the same histograms and
scalars, the best candidate per GROUP from scan_blocks must match the best
per-feature candidate within that group from scan_pair: same penalized
gain, absolute threshold lane, direction and left sums. The in-kernel
FixHistogram must match the explicit residual tensors the old eval_pair
materialized per split.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.analysis import strict_numerics
from lightgbm_tpu.ops.pallas_scan import (HAS_PALLAS, ScanLayout,
                                          build_block_scan_meta,
                                          scan_blocks, scan_pair)
from lightgbm_tpu.ops.split import FeatureMeta

if not HAS_PALLAS:  # pragma: no cover
    pytest.skip("pallas unavailable", allow_module_level=True)

W = 256


def _geometry():
    """3 groups: two EFB bundles + one singleton; mixed missing types."""
    group_of = np.array([0, 0, 1, 2], np.int32)
    ls = np.array([1, 9, 1, 0], np.int32)          # bundles reserve lane 0
    nb = np.array([8, 23, 60, 63], np.int32)
    mt = np.array([1, 2, 0, 2], np.int32)          # zero / nan / none / nan
    db = np.array([2, 0, 0, 5], np.int32)
    mf = np.array([0, 0, 0, 5], np.int32)
    needs_fix = np.array([True, True, True, False])
    penalty = np.array([1.0, 0.8, 1.0, 1.2])
    return group_of, ls, nb, mt, db, mf, needs_fix, penalty


def _feature_rows(blocks, group_of, Fp):
    """[2, Fp, W] per-feature rows: each feature gets a COPY of its whole
    group block (the pre-block-scan eval_pair layout)."""
    rows = np.take(blocks, group_of, axis=1)
    return np.pad(rows, ((0, 0), (0, Fp - len(group_of)), (0, 0)))


def _apply_fix(rows, sg, shr, ls, nb, mf, needs_fix):
    """The old out-of-kernel FixHistogram: most_freq lane gets
    child_total - window_sum for every needs-fix feature."""
    out = rows.copy()
    tot = np.array([sg, shr])
    for c in range(2):
        for v in range(2):
            for f in np.nonzero(needs_fix)[0]:
                wsum = rows[v][c, f, ls[f]:ls[f] + nb[f]].sum()
                out[v][c, f, ls[f] + mf[f]] += tot[v][c] - wsum
    return out


@pytest.mark.parametrize("seed", [0, 5])
def test_block_scan_matches_per_feature_kernel(seed):
    group_of, ls, nb, mt, db, mf, needs_fix, penalty = _geometry()
    F, G = len(group_of), 3
    rng = np.random.default_rng(seed)
    gb = rng.normal(size=(2, G, W)).astype(np.float32)
    hb = rng.random((2, G, W)).astype(np.float32) + 0.01
    # zero the lanes no feature owns so both paths see identical data
    meta_blk = build_block_scan_meta(group_of, ls, nb, mt, db, mf,
                                     needs_fix, penalty, G, W)
    has = meta_blk["has_owner"][:G]
    gb *= has
    hb *= has

    sg = np.array([3.0, -1.5], np.float32)
    shr = np.array([150.0, 90.0], np.float32)      # raw hessian sums
    sh = shr + 2e-15
    cnt = np.array([600.0, 360.0], np.float32)
    cf = cnt / sh
    l2, min_gain, md, mh = 0.5, 0.0, 5.0, 1e-3
    mgs = sg * sg / (sh + l2) + min_gain
    scal8 = np.stack([sg, sh, cnt, cf, np.full(2, md), np.full(2, mh),
                      mgs, np.full(2, l2)], axis=1).astype(np.float32)
    scal9 = np.concatenate([scal8, shr[:, None]], axis=1)

    # ---- per-feature reference: gather rows, explicit fix, scan_pair ---
    Fp = 8
    win_start = (group_of.astype(np.int64) * W + ls).astype(np.int32)
    meta = FeatureMeta(
        feat_id=jnp.zeros((G * W,), jnp.int32),
        bin_start=jnp.asarray(win_start),
        bin_end=jnp.asarray(win_start + nb),
        missing_type=jnp.asarray(mt),
        default_bin=jnp.asarray(db),
        monotone=jnp.zeros(F, jnp.int32),
        is_categorical=jnp.zeros(F, bool),
        penalty=jnp.asarray(penalty))
    layout = ScanLayout(meta, jnp.ones(F, bool), F, W, G * W,
                        win_off=jnp.asarray(ls))
    rows_g, rows_h = _apply_fix(
        [_feature_rows(gb, group_of, Fp), _feature_rows(hb, group_of, Fp)],
        sg, shr, ls, nb, mf, needs_fix)
    # strict-numerics harness: a silent f64 leak into either kernel's
    # f32 math fails here even if the numeric outputs still agree
    with strict_numerics():
        out_pair = np.asarray(scan_pair(
            jnp.asarray(scal8), jnp.asarray(rows_g), jnp.asarray(rows_h),
            layout.keep_r, layout.keep_f, layout.valid_r, layout.valid_f,
            layout.aux, interpret=True))              # [2, 8, Fp]

        # ---- block kernel: raw blocks, in-kernel fix ------------------
        Gp = meta_blk["masks"].shape[1]
        gbB = np.pad(gb, ((0, 0), (0, Gp - G), (0, 0)))
        hbB = np.pad(hb, ((0, 0), (0, Gp - G), (0, 0)))
        out_blk = np.asarray(scan_blocks(
            jnp.asarray(scal9), jnp.asarray(gbB), jnp.asarray(hbB),
            jnp.asarray(meta_blk["masks"]), do_fix=True, interpret=True))

    for c in range(2):
        for g in range(G):
            feats = np.nonzero(group_of == g)[0]
            gains_f = out_pair[c, 0, feats]
            bf = feats[np.argmax(gains_f)]
            bg, bt = out_blk[c, 0, g], out_blk[c, 1, g]
            if not np.isfinite(gains_f.max()):
                assert not np.isfinite(bg)
                continue
            np.testing.assert_allclose(bg, gains_f.max(), rtol=1e-4,
                                       atol=1e-5)
            assert bt == out_pair[c, 1, bf], (c, g, bf)
            assert out_blk[c, 2, g] == out_pair[c, 2, bf]
            np.testing.assert_allclose(out_blk[c, 3:6, g],
                                       out_pair[c, 3:6, bf],
                                       rtol=1e-4, atol=1e-4)


def test_block_scan_feature_mask_fold():
    """Folding a feature mask into the valid rows disables exactly that
    feature's window: the group's best moves to another member."""
    group_of, ls, nb, mt, db, mf, needs_fix, penalty = _geometry()
    G = 3
    rng = np.random.default_rng(2)
    gb = rng.normal(size=(2, G, W)).astype(np.float32)
    hb = rng.random((2, G, W)).astype(np.float32) + 0.01
    meta_blk = build_block_scan_meta(group_of, ls, nb, mt, db, mf,
                                     needs_fix, penalty, G, W)
    gb *= meta_blk["has_owner"][:G]
    hb *= meta_blk["has_owner"][:G]
    Gp = meta_blk["masks"].shape[1]
    gbB = jnp.asarray(np.pad(gb, ((0, 0), (0, Gp - G), (0, 0))))
    hbB = jnp.asarray(np.pad(hb, ((0, 0), (0, Gp - G), (0, 0))))
    sg, shr = np.array([2.0, 1.0]), np.array([120.0, 80.0])
    sh = shr + 2e-15
    cnt = np.array([480.0, 320.0])
    mgs = sg * sg / (sh + 0.5)
    scal9 = jnp.asarray(np.stack(
        [sg, sh, cnt, cnt / sh, np.full(2, 3.0), np.full(2, 1e-3), mgs,
         np.full(2, 0.5), shr], axis=1).astype(np.float32))

    def run(masks):
        with strict_numerics():
            return np.asarray(scan_blocks(scal9, gbB, hbB,
                                          jnp.asarray(masks),
                                          do_fix=False, interpret=True))

    base = run(meta_blk["masks"])
    # mask out group 0's feature that currently wins it
    owner = meta_blk["owner"]
    t0 = int(base[0, 1, 0])
    win_f = int(owner[0, t0])
    fmask = np.ones(len(group_of), np.float32)
    fmask[win_f] = 0.0
    fm_lane = np.where(meta_blk["has_owner"],
                       fmask[np.where(meta_blk["has_owner"],
                                      meta_blk["owner"], 0)], 0.0)
    masked = meta_blk["masks"].copy()
    masked[2:4] *= fm_lane[None]
    out = run(masked)
    t1 = int(out[0, 1, 0])
    assert not np.isfinite(out[0, 0, 0]) or owner[0, t1] != win_f
