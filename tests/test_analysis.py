"""Graft-lint: rule fixtures, engine mechanics, jaxpr audits, self-scan.

Layout mirrors the acceptance criteria:

* every registered JG rule is exercised against seeded-violation
  fixture snippets (positive) and clean twins (negative) — the
  parametrization is driven by the registry, so adding a rule without
  fixtures fails here by construction;
* engine mechanics: inline suppression, skip-file, baseline
  round-trip, unused-import autofix;
* the jaxpr audits run green (the two pinned invariants — no f64
  convert in persist-f32 kernels, serve ladder bound — are tier-1);
* the repo self-scan: ZERO unsuppressed findings, same gate as
  `python -m lightgbm_tpu.analysis`.
"""
import json
import os
import textwrap

import pytest

from lightgbm_tpu.analysis import (GraftlintConfig, all_auditors,
                                   load_config, run_auditors, run_audits,
                                   run_lint)
from lightgbm_tpu.analysis.config import _parse_table
from lightgbm_tpu.analysis.lint import (apply_baseline, iter_py_files,
                                        lint_source, load_baseline,
                                        prune_baseline, write_baseline)
from lightgbm_tpu.analysis.rules import all_rules

OPS = "lightgbm_tpu/ops/fake.py"          # hot path, kernel-bearing
COLD = "lightgbm_tpu/data/fake.py"        # not a hot path


def _ids(findings, rule=None):
    return [f.rule for f in findings
            if not f.suppressed and (rule is None or f.rule == rule)]


def _lint(src, relpath=OPS, **cfg):
    config = GraftlintConfig(**cfg) if cfg else GraftlintConfig()
    return lint_source(textwrap.dedent(src), relpath, config)


# ---------------------------------------------------------------------------
# per-rule fixtures: positive (fires) + negative (clean twin)
# ---------------------------------------------------------------------------

FIXTURES = {
    "JG001": {
        "positive": """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                if jnp.any(x > 0):
                    return x + 1
                return x
            """,
        "negative": """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x, flag: bool):
                if flag:                       # static python value: fine
                    return x + 1
                return jax.lax.cond(jnp.any(x > 0),
                                    lambda v: v + 1, lambda v: v, x)

            def host(x):
                if jnp.any(x > 0):             # not a jitted scope
                    return 1
                return 0
            """,
    },
    "JG002": {
        "positive": """
            import numpy as np

            def serve(batches, dev):
                out = []
                for b in batches:
                    out.append(np.asarray(dev(b)))     # per-batch sync
                    total = dev(b).sum().item()        # and another
                    scale = float(dev(b)[0])           # and another
                return out, total, scale
            """,
        "negative": """
            import numpy as np

            def serve(batches, dev):
                outs = [dev(b) for b in batches]
                return np.asarray(outs)                # one batched sync
            """,
    },
    "JG003": {
        "positive": """
            import jax.numpy as jnp

            def setup(m):
                pad = jnp.zeros((4, 4))                # f64 under x64
                half = jnp.asarray(0.5)                # f64 under x64
                y = jnp.where(m, 1.0, -1.0)            # f64 select
                return pad, half, y

            def _scan_kernel(hb, cf):
                return jnp.floor(hb * cf + 0.5)        # kernel literal
            """,
        "negative": """
            import jax.numpy as jnp

            def setup(m, x):
                pad = jnp.zeros((4, 4), jnp.float32)
                half = jnp.asarray(0.5, jnp.float32)
                y = jnp.where(m, 1.0, -1.0).astype(x.dtype)
                keep = jnp.where(m, 1.0, x)            # one literal: weak
                return pad, half, y, keep

            def _scan_kernel(hb, cf):
                return jnp.floor(hb * cf + jnp.float32(0.5))

            def host_math(a):
                return a * 0.5                         # not a kernel
            """,
    },
    "JG004": {
        "positive": """
            import jax

            from lightgbm_tpu.ops.pallas_grow import make_level_pass

            def train(trees, step, geo):
                outs = []
                for t in trees:
                    f = jax.jit(step)                  # recompile storm
                    outs.append(f(t))
                return outs

            def grow_levels(levels, geo):
                for lv in levels:
                    lp = make_level_pass(*geo)         # builder per level:
                    lv.run(lp)                         # same storm, hidden
            """,
        "negative": """
            import jax

            from lightgbm_tpu.ops.pallas_grow import make_level_pass

            def train(trees, step):
                f = jax.jit(step)                      # hoisted
                outs = []
                for t in trees:
                    outs.append(f(t))

                def make(c):                           # builder in loop is
                    return jax.jit(lambda x: x + c)    # a def, not a call
                return outs, [make(c) for c in (1, 2)]

            def grow_levels(levels, geo):
                lp = make_level_pass(*geo)             # once per geometry
                for lv in levels:
                    lv.run(lp)
            """,
    },
    "JG005": {
        "positive": """
            import time
            import numpy as np

            def sample(n):
                idx = np.random.permutation(n)         # global RNG
                rng = np.random.default_rng(time.time())   # clock seed
                return idx, rng
            """,
        "negative": """
            import numpy as np

            def sample(n, seed):
                rng = np.random.default_rng(seed)
                return rng.permutation(n), np.random.RandomState(seed)
            """,
    },
    "JG006": {
        "positive": """
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu

            def kernel_call(f, shape):
                return pl.pallas_call(f, out_shape=shape)
            """,
        "negative": """
            from .pallas_compat import HAS_PALLAS, pl, pltpu

            def kernel_call(f, shape):
                if not HAS_PALLAS:
                    return None
                return pl.pallas_call(f, out_shape=shape)
            """,
    },
    "JG007": {
        "positive": """
            import json
            from typing import Dict, List

            def f(d: Dict) -> Dict:
                return d
            """,
        "negative": """
            import json
            from typing import Dict

            try:
                import exotic_backend              # probing idiom: skipped
            except ImportError:
                exotic = None

            import unused_but_marked  # noqa: F401

            def f(d: Dict) -> str:
                return json.dumps(d)
            """,
    },
    # JG009 is scoped to the collective paths (parallel/, resilience/)
    "JG009": {
        "relpath": "lightgbm_tpu/parallel/fake.py",
        "positive": """
            import numpy as np
            from jax.experimental import multihost_utils

            def sync_counts(n_local):
                return multihost_utils.process_allgather(   # no guard
                    np.asarray([n_local], np.int64))
            """,
        "negative": """
            import numpy as np
            from jax.experimental import multihost_utils

            from lightgbm_tpu.resilience import retry as resilience_retry

            def sync_counts(n_local):
                return resilience_retry.guard(
                    "allgather:row_counts",
                    multihost_utils.process_allgather,
                    np.asarray([n_local], np.int64))

            def sync_lazy(arr):
                # a closure handed to guard still runs under its deadline
                return resilience_retry.guard(
                    "allgather:lazy",
                    lambda: multihost_utils.process_allgather(arr))
            """,
    },
    # JG010 is scoped to ops//predict/ MINUS the narrow-ok-paths
    # allowlist; the fixture relpath (ops/fake.py) is not allowlisted
    "JG010": {
        "positive": """
            import jax.numpy as jnp
            import numpy as np

            def shrink(x, leaves):
                small = x.astype(jnp.float32)          # unblessed narrow
                tiny = leaves.astype("bfloat16")       # string form too
                half = leaves.astype(dtype=jnp.float16)  # kwarg form
                q = jnp.asarray(x, dtype=jnp.int8)     # quantized payload
                return small, tiny, half, q
            """,
        "negative": """
            import jax.numpy as jnp

            def widen(x, y):
                big = x.astype(jnp.float64)            # widening: fine
                dyn = x.astype(y.dtype)                # dynamic: fine
                arr = jnp.asarray(x, dtype=jnp.float64)
                return big, dyn, arr
            """,
    },
    # JG008 is scoped to the resilience durability paths; its fixtures
    # carry their own relpath (the "relpath" key overrides the OPS default)
    "JG008": {
        "relpath": "lightgbm_tpu/resilience/fake.py",
        "positive": """
            import json

            def save_state(path, state):
                with open(path, "w") as f:         # in-place: torn on kill
                    json.dump(state, f)
            """,
        "negative": """
            import json
            import os

            def save_state(path, state):
                tmp_path = path + ".tmp"
                with open(tmp_path, "w") as f:
                    json.dump(state, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp_path, path)

            def load_state(path):
                with open(path) as f:              # reads are never flagged
                    return json.load(f)
            """,
    },
    # JG011/JG012 are scoped to the threaded host layer
    # (concurrency_paths); their fixtures live in serving/
    "JG011": {
        "relpath": "lightgbm_tpu/serving/fake.py",
        "positive": """
            import threading

            class Server:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._count = 0
                    self._thread = None

                def start(self):
                    self._thread = threading.Thread(target=self._loop,
                                                    daemon=True)
                    self._thread.start()

                def _loop(self):
                    self._count += 1          # racing submit(), no lock

                def submit(self):
                    self._count += 1
            """,
        "negative": """
            import threading

            class Server:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._count = 0
                    self._thread = None

                def start(self):
                    self._thread = threading.Thread(target=self._loop,
                                                    daemon=True)
                    self._thread.start()

                def _loop(self):
                    with self._lock:
                        self._count += 1

                def submit(self):
                    with self._lock:
                        self._count += 1
            """,
    },
    "JG012": {
        "relpath": "lightgbm_tpu/serving/fake.py",
        "positive": """
            import threading

            class Pool:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._done = 0

                def flush(self, fut):
                    with self._lock:
                        out = fut.result()    # convoy: blocks lock-holders
                        self._done += 1
                    return out
            """,
        "negative": """
            import threading

            class Pool:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._done = 0

                def flush(self, fut):
                    out = fut.result()        # block FIRST, then lock
                    with self._lock:
                        self._done += 1
                    return out
            """,
    },
}


def test_every_rule_has_fixtures():
    ids = {r.id for r in all_rules()}
    assert ids == set(FIXTURES), "every JG rule needs fixture snippets"
    assert ids == {"JG001", "JG002", "JG003", "JG004", "JG005", "JG006",
                   "JG007", "JG008", "JG009", "JG010", "JG011", "JG012"}


def test_jg010_scope_and_allowlist():
    """The same narrowing cast is fine outside ops//predict/ (host
    tooling narrows freely) and inside an allowlisted module (the
    blessed kernels); predict/ is in scope."""
    pos = FIXTURES["JG010"]["positive"]
    assert _ids(_lint(pos, relpath=COLD), "JG010") == []
    assert _ids(_lint(pos,
                      relpath="lightgbm_tpu/ops/pallas_histogram.py"),
                "JG010") == []
    assert len(_ids(_lint(pos, relpath="lightgbm_tpu/predict/fake.py"),
                    "JG010")) == 4


def test_jg009_outside_scope_is_silent():
    """The same direct collective call is fine outside the collective
    paths (a test helper gathering once at setup is not the hot DCN
    contract)."""
    hits = _ids(_lint(FIXTURES["JG009"]["positive"], relpath=COLD),
                "JG009")
    assert hits == []


@pytest.mark.parametrize("rule_id", sorted(FIXTURES))
def test_rule_fires_on_seeded_violation(rule_id):
    rp = FIXTURES[rule_id].get("relpath", OPS)
    hits = _ids(_lint(FIXTURES[rule_id]["positive"], relpath=rp), rule_id)
    assert hits, "%s stayed silent on its seeded violation" % rule_id


@pytest.mark.parametrize("rule_id", sorted(FIXTURES))
def test_rule_silent_on_clean_twin(rule_id):
    rp = FIXTURES[rule_id].get("relpath", OPS)
    hits = _ids(_lint(FIXTURES[rule_id]["negative"], relpath=rp), rule_id)
    assert not hits, "%s false-positived on its clean twin" % rule_id


def test_jg008_outside_scope_is_silent():
    """The same in-place write is fine outside the durability paths (the
    CLI writing a predictions file is not a checkpoint)."""
    hits = _ids(_lint(FIXTURES["JG008"]["positive"], relpath=COLD), "JG008")
    assert hits == []


def test_jg002_fixture_counts_and_cold_path():
    pos = FIXTURES["JG002"]["positive"]
    assert len(_ids(_lint(pos), "JG002")) == 3     # asarray + item + float
    assert _ids(_lint(pos, relpath=COLD), "JG002") == []


def test_jg003_flags_each_shape_once():
    hits = _ids(_lint(FIXTURES["JG003"]["positive"]), "JG003")
    assert len(hits) == 4   # zeros, asarray-literal, where, kernel literal


def test_jg007_fix_wraps_long_from_imports(tmp_path):
    """The rewritten statement must stay valid Python: long from-imports
    wrap in parentheses; plain `import a, b` (no legal paren form) is
    left long rather than broken."""
    import ast as ast_mod

    pkg = tmp_path / "lightgbm_tpu"
    pkg.mkdir()
    mod = pkg / "mod.py"
    mod.write_text(
        "import json, very_long_module_name_aaaa, "
        "very_long_module_name_bbbb, very_long_module_name_cccc\n"
        "from some.rather.deep.package.path import (unused_name_xx, "
        "kept_name_aaaaaaaa, kept_name_bbbbbbbb, kept_name_cccccccc)\n"
        "print(very_long_module_name_aaaa, very_long_module_name_bbbb,\n"
        "      very_long_module_name_cccc, kept_name_aaaaaaaa,\n"
        "      kept_name_bbbbbbbb, kept_name_cccccccc)\n")
    cfg = GraftlintConfig(root=str(tmp_path), baseline="baseline.json")
    report = run_lint(config=cfg, autofix=True)
    assert report.autofixed == 2
    fixed = mod.read_text()
    ast_mod.parse(fixed)                     # must still be valid Python
    assert "json" not in fixed and "unused_name_xx" not in fixed
    from_lines = [ln for ln in fixed.splitlines()
                  if ln.startswith("from ")]
    assert all(len(ln) <= 79 for ln in from_lines), from_lines


def test_jg007_autofix_idempotent(tmp_path):
    """Running --autofix twice must be a byte-for-byte no-op. The pinned
    regression: `import os` next to `from os import path` — os's only
    other mention is inside the deletable second import, so pass 1 used
    to keep it and pass 2 deleted it. Both go in pass 1 now."""
    pkg = tmp_path / "lightgbm_tpu"
    pkg.mkdir()
    mod = pkg / "mod.py"
    mod.write_text("import os\n"
                   "from os import path\n"
                   "from some.rather.deep.package.path import ("
                   "unused_name_xx, kept_name_aaaaaaaa, "
                   "kept_name_bbbbbbbb, kept_name_cccccccc)\n"
                   "\n"
                   "def f():\n"
                   "    return (kept_name_aaaaaaaa, kept_name_bbbbbbbb,\n"
                   "            kept_name_cccccccc)\n")
    cfg = GraftlintConfig(root=str(tmp_path), baseline="baseline.json")
    r1 = run_lint(config=cfg, autofix=True)
    t1 = mod.read_text()
    assert r1.autofixed == 3                  # os + path + unused_name_xx
    assert "import os" not in t1 and "unused_name_xx" not in t1
    r2 = run_lint(config=cfg, autofix=True)
    assert r2.autofixed == 0
    assert mod.read_text() == t1, "second --autofix pass changed bytes"


def test_prune_baseline_drops_stale_entries(tmp_path):
    """Stale baseline entries (fixed or deleted findings) are dropped;
    live ones are kept with counts clamped to what still matches —
    a stale suppression can't sit around hiding a regression."""
    src = """
        import jax.numpy as jnp

        def setup():
            return jnp.zeros((4,))
        """
    findings = _lint(src)
    bl = str(tmp_path / "b.json")
    write_baseline(findings, bl)
    # graft in a stale entry + an overcounted live one
    data = json.load(open(bl))
    data["findings"].append({"rule": "JG003", "path": OPS,
                             "snippet": "gone = jnp.ones((4,))",
                             "count": 2})
    data["findings"][0]["count"] += 3        # overcount the live entry
    json.dump(data, open(bl, "w"))
    kept, dropped = prune_baseline(_lint(src), bl)
    assert (kept, dropped) == (1, 5)         # stale 2 + overcount 3
    pruned = load_baseline(bl)
    assert sum(pruned.values()) == 1
    fresh = _lint(src)
    apply_baseline(fresh, pruned)
    assert _ids(fresh) == []                 # live entry still suppresses
    # idempotent: nothing left to prune
    assert prune_baseline(_lint(src), bl) == (1, 0)


def test_write_baseline_keeps_grandfathered(tmp_path):
    """Refreshing the baseline from a report whose findings are already
    baseline-suppressed must re-emit them, not silently drop them (the
    CLI --write-baseline path)."""
    src = """
        import jax.numpy as jnp

        def setup():
            return jnp.zeros((4,))
        """
    findings = _lint(src)
    bl = str(tmp_path / "b.json")
    assert write_baseline(findings, bl) == 1
    again = _lint(src)
    apply_baseline(again, load_baseline(bl))
    assert all(f.suppression == "baseline" for f in again)
    # the refresh the CLI performs: full findings list, suppressed or not
    assert write_baseline(again, bl) == 1
    assert load_baseline(bl)


def test_jg007_fix_rewrites_imports(tmp_path):
    pkg = tmp_path / "lightgbm_tpu"
    pkg.mkdir()
    mod = pkg / "mod.py"
    mod.write_text(textwrap.dedent("""\
        import json
        from typing import Dict, List

        def f(d: Dict) -> Dict:
            return d
        """))
    cfg = GraftlintConfig(root=str(tmp_path), baseline="baseline.json")
    report = run_lint(config=cfg, autofix=True)
    assert report.autofixed == 2
    assert [f for f in report.findings if not f.suppressed] == []
    fixed = mod.read_text()
    assert "import json" not in fixed
    assert "from typing import Dict" in fixed and "List" not in fixed


# ---------------------------------------------------------------------------
# engine mechanics
# ---------------------------------------------------------------------------

def test_inline_suppression_same_line_and_above():
    src = """
        import jax.numpy as jnp

        def setup():
            a = jnp.zeros((4,))  # graftlint: disable=JG003
            # graftlint: disable=JG003
            b = jnp.zeros((4,))
            c = jnp.zeros((4,))
            return a, b, c
        """
    fs = [f for f in _lint(src) if f.rule == "JG003"]
    assert [f.suppressed for f in fs] == [True, True, False]
    assert {f.suppression for f in fs if f.suppressed} == {"inline"}


def test_skip_file_marker():
    src = "# graftlint: skip-file\nimport jax.numpy as jnp\n" \
          "bad = jnp.zeros((4,))\n"
    assert lint_source(src, OPS, GraftlintConfig()) == []


def test_baseline_roundtrip(tmp_path):
    src = """
        import jax.numpy as jnp

        def setup():
            a = jnp.zeros((4,))
            return a, jnp.zeros((8,))
        """
    findings = _lint(src)
    assert len(_ids(findings)) == 2
    bl_path = str(tmp_path / "baseline.json")
    assert write_baseline(findings, bl_path) == 2
    baseline = load_baseline(bl_path)
    fresh = _lint(src)
    apply_baseline(fresh, baseline)
    assert _ids(fresh) == []
    assert all(f.suppression == "baseline" for f in fresh)
    # baseline matches by source line, not line number: new unrelated
    # findings stay unsuppressed
    grown = _lint(src.rstrip() + "\n\n        more = jnp.zeros((2,))\n")
    apply_baseline(grown, baseline)
    assert len(_ids(grown)) == 1


def test_config_table_parsing():
    table = _parse_table(textwrap.dedent("""\
        [tool.other]
        x = 1
        [tool.graftlint]
        include = ["lightgbm_tpu"]
        exclude = [
            "__pycache__",
            "native",
        ]
        baseline = "b.json"
        disable = []
        [tool.after]
        y = 2
        """))
    assert table["include"] == ["lightgbm_tpu"]
    assert table["exclude"] == ["__pycache__", "native"]
    assert table["baseline"] == "b.json"
    assert table["disable"] == []


def test_repo_config_loads_and_walks():
    cfg = load_config()
    files = iter_py_files(cfg)
    assert any(p.endswith("ops/pallas_scan.py") for p in files)
    assert not any("__pycache__" in p for p in files)
    assert cfg.is_hot_path("lightgbm_tpu/ops/grow.py")
    assert not cfg.is_hot_path("lightgbm_tpu/data/dataset.py")


# ---------------------------------------------------------------------------
# jaxpr audits (the two pinned invariants are tier-1 here)
# ---------------------------------------------------------------------------

def test_audits_all_green():
    results = {r.name: r for r in run_audits()}
    assert set(results) == {
        "hist_window_f32", "scan_pair_f32", "scan_blocks_f32",
        "persist_split_pass", "persist_level_pass",
        "predict_traversal_f32", "predict_donation",
        "serve_ladder_bound", "fused_iteration"}
    bad = {n: r.detail for n, r in results.items() if not r.ok}
    assert not bad, bad


def test_audit_catches_f64_convert():
    """The f64 detector actually detects: a deliberately-widening
    program must fail the same check the kernels pass."""
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.analysis.jaxpr_audit import find_f64_converts

    def leaky(x):
        return x.astype(jnp.float64) * 2.0

    closed = jax.make_jaxpr(leaky)(
        jax.ShapeDtypeStruct((8,), jnp.float32))
    assert find_f64_converts(closed.jaxpr)


def test_audit_catches_callback_in_loop():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from lightgbm_tpu.analysis.jaxpr_audit import find_host_prims_in_loops

    def bad(x):
        def body(_, v):
            return v + jax.pure_callback(
                lambda a: np.asarray(a), jax.ShapeDtypeStruct((), v.dtype),
                v[0])
        return jax.lax.fori_loop(0, 3, body, x)

    closed = jax.make_jaxpr(bad)(jax.ShapeDtypeStruct((4,), jnp.float32))
    assert find_host_prims_in_loops(closed.jaxpr)


# ---------------------------------------------------------------------------
# the gate: repo self-scan
# ---------------------------------------------------------------------------

def test_repo_self_scan_clean():
    """`python -m lightgbm_tpu.analysis` must exit 0: zero unsuppressed
    findings over the whole package (baseline-suppressed grandfathered
    ones are allowed, parse errors are not)."""
    report = run_lint()
    assert report.parse_errors == []
    bad = [(f.path, f.line, f.rule, f.message)
           for f in report.unsuppressed]
    assert not bad, "unsuppressed graft-lint findings:\n%s" % \
        "\n".join("%s:%d %s %s" % b for b in bad)
    assert report.files_scanned > 60


def test_baseline_is_empty():
    """The baseline must shrink, never grow — and since the PR 8
    burn-down of the 8 grandfathered JG002 multihost setup-loop syncs it
    is EMPTY. A PR that adds entries has to justify itself here."""
    cfg = load_config()
    with open(cfg.baseline_path()) as f:
        data = json.load(f)
    assert data["findings"] == [], data["findings"]


def test_lint_lands_on_telemetry_counters():
    """Findings/files land on `analysis::*` counters when telemetry is
    on, so services embedding the gate see lint drift next to their
    perf counters."""
    from lightgbm_tpu.telemetry import events

    prev = events.mode()
    events.enable("timers")
    events.reset()
    try:
        run_lint(paths=["lightgbm_tpu/analysis/lint.py"])
        counts = events.counts_snapshot()
        assert counts.get("analysis::files_scanned", 0) == 1
        assert "analysis::findings" in counts
    finally:
        events.reset()
        if prev == events.OFF:
            events.disable()


def test_cli_smoke(capsys):
    from lightgbm_tpu.analysis.__main__ import main
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "JG001" in out and "JG007" in out and "JG010" in out
    # --list-audits mirrors --list-rules for the audit registry
    assert main(["--list-audits"]) == 0
    out = capsys.readouterr().out
    for name in ("hist_window", "precision_flow", "transfer",
                 "quant_certify", "perf_sentinel"):
        assert name in out, name
    # lint-only over one file: exits 0 and prints the summary line
    assert main(["lightgbm_tpu/analysis/lint.py", "--no-audit"]) == 0
    assert "graft-lint:" in capsys.readouterr().out
    # the budget tables render without running the gate
    assert main(["--budgets"]) == 0
    out = capsys.readouterr().out
    assert "resource budgets" in out and "hist_window" in out


# ---------------------------------------------------------------------------
# whole-program auditors: fixtures enumerated from the registry
# ---------------------------------------------------------------------------
#
# Same contract as the JG rules: every registered auditor needs a
# seeded-violation payload its check_fixture() flags and a clean twin it
# stays silent on — an auditor added without fixtures fails here by
# construction.

AUDITOR_FIXTURES = {
    "collective_order": {
        # rank 0 gathers, everyone else never arrives: deadlock
        "positive": """
            from jax.experimental import multihost_utils

            from lightgbm_tpu.resilience import retry as resilience_retry

            def sync_stats(rank, stats):
                if rank == 0:
                    return resilience_retry.guard(
                        "allgather:stats",
                        multihost_utils.process_allgather, stats)
                return stats
            """,
        # unconditional collective; only the logging is rank-dependent
        "negative": """
            from jax.experimental import multihost_utils

            from lightgbm_tpu.resilience import retry as resilience_retry

            def sync_stats(rank, stats):
                agg = resilience_retry.guard(
                    "allgather:stats",
                    multihost_utils.process_allgather, stats)
                if rank == 0:
                    print(agg)
                return agg
            """,
    },
    "resource_budget": {
        # a 4000-group unbundled monster: kernels blow VMEM, planes
        # blow HBM
        "positive": {"rows": 50_000_000, "features": 4000,
                     "groups": 4000, "bundled": False},
        "negative": {"rows": 1_000_000, "features": 28, "groups": 28,
                     "bundled": False},
    },
    "compile_surface": {
        # a per-iteration Python int marked static: unbounded recompiles
        "positive": """
            import functools

            import jax

            @functools.partial(jax.jit, static_argnames=("n_iter",))
            def step(x, n_iter):
                return x * n_iter
            """,
        "negative": """
            import functools

            import jax

            @functools.partial(jax.jit, static_argnames=("interpret",))
            def step(x, interpret):
                return x * 2
            """,
    },
    # f64 gains narrowed to f32 BEFORE the argmax (the tie-flip
    # geometry) vs a range-proven narrowing feeding plain arithmetic
    "precision_flow": {
        "positive": {"program": "tie_flip"},
        "negative": {"program": "bounded_narrow"},
    },
    # a host callback inside a scan body vs the same loop kept on-device
    "transfer": {
        "positive": {"program": "callback_in_scan"},
        "negative": {"program": "clean_scan"},
    },
    # a module that builds a persist scan driver without any path to
    # the numerics::* health flush vs the same module flushing
    "health_covered": {
        "positive": """
            from lightgbm_tpu.ops.grow_persist import make_scan_driver

            def build(gr, gc, k, fn):
                return make_scan_driver(gr, gc, k, fn)
            """,
        "negative": """
            from lightgbm_tpu.ops.grow_persist import make_scan_driver
            from lightgbm_tpu.telemetry.health import flush_device_stats

            def build_and_train(gr, gc, k, fn, pay, args):
                driver = make_scan_driver(gr, gc, k, fn)
                pay, stacked, stats = driver(pay, *args)
                flush_device_stats(stats[2:])
                return stacked
            """,
    },
    # int8 at full plane scale blows the split-decision budget; int16
    # at the higgs geometry certifies (the shipped certificate)
    "quant_certify": {
        "positive": {"name": "hist_int8", "kind": "histogram",
                     "target": "int8", "stochastic": True,
                     "rows_per_rank": 1_312_500, "ranks": 8,
                     "bins": 256, "g_max": 1.0, "h_max": 0.25,
                     "lambda": 1.0},
        "negative": {"name": "hist_int16", "kind": "histogram",
                     "target": "int16", "stochastic": True,
                     "rows_per_rank": 1_312_500, "ranks": 8,
                     "bins": 256, "g_max": 1.0, "h_max": 0.25,
                     "lambda": 1.0},
    },
    # a service-loop thread and submit() racing on an unguarded counter
    # vs the same pair sharing the lock (the deeper per-analysis cases —
    # blocking-hold, lock-order cycles, guarded-by — live in
    # tests/test_concurrency_audit.py)
    "concurrency": {
        "positive": """
            import threading

            class Server:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._count = 0
                    self._thread = None

                def start(self):
                    self._thread = threading.Thread(target=self._loop,
                                                    daemon=True)
                    self._thread.start()

                def _loop(self):
                    self._count += 1

                def submit(self):
                    self._count += 1
            """,
        "negative": """
            import threading

            class Server:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._count = 0
                    self._thread = None

                def start(self):
                    self._thread = threading.Thread(target=self._loop,
                                                    daemon=True)
                    self._thread.start()

                def _loop(self):
                    with self._lock:
                        self._count += 1

                def submit(self):
                    with self._lock:
                        self._count += 1
            """,
    },
}


def test_every_auditor_has_fixtures():
    assert set(AUDITOR_FIXTURES) == set(all_auditors()), \
        "every registered auditor needs fixture payloads"


@pytest.mark.parametrize("name", sorted(AUDITOR_FIXTURES))
def test_auditor_fires_on_seeded_violation(name):
    mod = all_auditors()[name]
    payload = AUDITOR_FIXTURES[name]["positive"]
    if isinstance(payload, str):
        payload = textwrap.dedent(payload)
    hits = mod.check_fixture(payload)
    assert hits, "%s stayed silent on its seeded violation" % name


@pytest.mark.parametrize("name", sorted(AUDITOR_FIXTURES))
def test_auditor_silent_on_clean_twin(name):
    mod = all_auditors()[name]
    payload = AUDITOR_FIXTURES[name]["negative"]
    if isinstance(payload, str):
        payload = textwrap.dedent(payload)
    hits = mod.check_fixture(payload)
    assert not hits, "%s false-positived on its clean twin: %s" \
        % (name, hits)


def test_collective_auditor_divergence_forms():
    """Beyond the registry fixture: symmetric branches are rank-safe,
    early exits under rank branches are not, and derived rank values
    (cuts[rank]) taint through arithmetic but not through calls."""
    from lightgbm_tpu.analysis import collective_audit as co
    symmetric = """
        from jax.experimental import multihost_utils

        from lightgbm_tpu.resilience import retry as resilience_retry

        def sync(rank, a, b):
            if rank == 0:
                out = resilience_retry.guard(
                    "allgather:x", multihost_utils.process_allgather, a)
            else:
                out = resilience_retry.guard(
                    "allgather:x", multihost_utils.process_allgather, b)
            return out
        """
    assert co.check_fixture(textwrap.dedent(symmetric)) == []
    early_exit = """
        from jax.experimental import multihost_utils

        from lightgbm_tpu.resilience import retry as resilience_retry

        def sync(rank, cuts, stats):
            start = cuts[rank]
            if start < 0:
                return None
            return resilience_retry.guard(
                "allgather:stats",
                multihost_utils.process_allgather, stats)
        """
    hits = co.check_fixture(textwrap.dedent(early_exit))
    assert hits and "early exit" in hits[0]
    call_barrier = """
        from jax.experimental import multihost_utils

        from lightgbm_tpu.resilience import retry as resilience_retry

        def sync(rank, stats):
            counts = resilience_retry.guard(
                "allgather:counts",
                multihost_utils.process_allgather, stats)
            if counts.sum() > 0:     # collective result: rank-uniform
                return resilience_retry.guard(
                    "allgather:stats",
                    multihost_utils.process_allgather, stats)
            return None
        """
    assert co.check_fixture(textwrap.dedent(call_barrier)) == []


def test_collective_trace_extracts_repo_sites():
    """The abstract trace covers the known DCN call sites with their
    guard labels — the artifact the item-2 collectives rewrite diffs."""
    from lightgbm_tpu.analysis import collective_audit as co
    trace = co.extract_repo_trace()
    names = {s["name"] for s in trace["sites"] if s["name"]}
    assert {"allgather:binning_sizes", "allgather:binning_mappers",
            "allreduce:metrics_values", "allgather:row_counts",
            # the ONE resume-agreement exchange (reshard.agree_generation)
            # every resuming rank joins — same-mesh and elastic alike —
            # guarded and rank-uniform like any other DCN site
            "allgather:resume_agree"} <= names
    assert all(s["guarded"] for s in trace["sites"])
    assert trace["findings"] == []
    # the item-2 wire format: in-program mesh collectives (quantized
    # plane reductions + the PV-Tree vote allgather) ride the trace as
    # mesh_sites — the top-k vote exchange and every histogram-plane
    # reduce site must be labeled and present in BOTH growers
    mesh = trace["mesh_sites"]
    assert all(s["mesh"] and s["name"] for s in mesh), \
        "every mesh-collective wrapper call needs a literal label"
    mesh_names = {s["name"] for s in mesh}
    assert {"allgather:vote_topk", "psum:vote_windows",
            "psum:vote_planes", "psum:hist_root", "psum:hist_level",
            "psum:hist_split", "psum:hist_plane"} <= mesh_names
    by_path = {}
    for s in mesh:
        by_path.setdefault(s["path"], set()).add(s["name"])
    assert "allgather:vote_topk" in by_path["lightgbm_tpu/ops/grow.py"]
    assert "allgather:vote_topk" \
        in by_path["lightgbm_tpu/ops/grow_persist.py"]


def test_resource_audit_tracks_kernel_formulas():
    """The request column must come from the kernels' own helpers — if a
    kernel formula changes, the audit sees the new number without
    edits here."""
    from lightgbm_tpu.analysis import resource_audit as ra
    from lightgbm_tpu.ops.pallas_scan import scan_pair_vmem_bytes
    from lightgbm_tpu.telemetry.devices import get_profile
    est = ra.estimate_scan_pair(ra.BENCH_SHAPES["yahoo"],
                                get_profile("v5e"))
    assert est.request == scan_pair_vmem_bytes(704, 256)
    assert est.ok


def test_resource_audit_profile_budgets_differ():
    """v4's 32MB VMEM cannot host the 100MB-class kernel requests the
    v5e tuning assumes — the per-profile budget check must say so."""
    from lightgbm_tpu.analysis import resource_audit as ra
    from lightgbm_tpu.telemetry.devices import get_profile
    kernels, _ = ra.estimate_all(profile=get_profile("v4"))
    assert any(not k.ok for k in kernels)
    kernels5, hbm5 = ra.estimate_all(profile=get_profile("v5e"))
    assert all(k.ok for k in kernels5) and all(h.ok for h in hbm5)


def test_compile_audit_enumerates_known_entry_points():
    """The AST walk must see the real jit surface: the kernel entry
    points, the predict runtime's static raw flag, and the factories."""
    from lightgbm_tpu.analysis import compile_audit as ca
    surf = ca.compile_surface()
    funcs = {s["func"] for s in surf["sites"]}
    assert {"hist_window", "scan_pair", "scan_blocks",
            "build_histogram"} <= funcs
    assert any(s["static_nums"] == [1] for s in surf["sites"]
               if "runtime.py" in s["path"])
    assert surf["serve_ladder_bound"] == 9     # ceil(log2(65536/256))+1
    assert surf["total_bound"] <= 64
    assert all(not s["unbounded"] for s in surf["sites"])


def test_auditors_all_green_on_repo():
    """The whole-program auditors pass on the repo itself — the same
    results the CLI gate appends to the jaxpr audits."""
    results = {r.name: r for r in run_auditors()}
    assert set(results) == {"collective_order", "collective_guarded",
                            "collective_observed", "vmem_budget",
                            "hbm_budget", "compile_surface",
                            "precision_flow", "transfer",
                            "quant_certify", "health_covered",
                            "concurrency_discipline",
                            "concurrency_blocking_hold",
                            "concurrency_lock_order"}
    bad = {n: r.detail for n, r in results.items() if not r.ok}
    assert not bad, bad


def test_transfer_auditor_flags_large_all_gather():
    """Beyond the registry fixture: the replicated-intermediate arm —
    an in-program all_gather whose output exceeds the size threshold
    is a finding, the same program under a lax threshold is not."""
    from lightgbm_tpu.analysis import transfer_audit as ta
    hits = ta.check_fixture({"program": "all_gather_large",
                             "threshold": 1 << 16})
    assert hits and "replicated" in hits[0]
    assert ta.check_fixture({"program": "all_gather_large",
                             "threshold": 1 << 30}) == []


def test_gate_flips_on_seeded_tie_flip(monkeypatch, capsys):
    """LGBTPU_SEED_TIE_FLIP=1 arms the seeded tie-flip program as a
    live precision_flow audit: the CLI gate must exit 1."""
    from lightgbm_tpu.analysis.__main__ import main
    from lightgbm_tpu.analysis.precision_audit import SEED_TIE_FLIP_ENV
    monkeypatch.setenv(SEED_TIE_FLIP_ENV, "1")
    code = main(["--json", "--audit-only"])
    payload = json.loads(capsys.readouterr().out)
    assert code == 1 and payload["exit_code"] == 1
    bad = [a for a in payload["audits"]
           if a["name"] == "precision_flow" and not a["ok"]]
    assert bad and "tie_flip" in bad[0]["detail"]


def test_gate_flips_on_seeded_custom_jvp_f64(monkeypatch, capsys):
    """LGBTPU_SEED_CUSTOM_JVP_F64=1 arms the f64-const-in-custom_jvp
    fixture as a live jaxpr audit: the CLI gate must exit 1 with the
    const named (the class the pre-dataflow walk missed)."""
    from lightgbm_tpu.analysis.__main__ import main
    from lightgbm_tpu.analysis.jaxpr_audit import SEED_CUSTOM_JVP_ENV
    monkeypatch.setenv(SEED_CUSTOM_JVP_ENV, "1")
    code = main(["--json", "--audit-only"])
    payload = json.loads(capsys.readouterr().out)
    assert code == 1 and payload["exit_code"] == 1
    bad = [a for a in payload["audits"]
           if a["name"] == "seeded_custom_jvp_f64"]
    assert bad and not bad[0]["ok"]
    assert "const f64" in bad[0]["detail"]


def test_cli_gate_json_green(capsys):
    """`python -m lightgbm_tpu.analysis --json` — the EXACT gate
    pre-commit runs — exits 0 on the repo, reports all five new audit
    results, and ships the auditor artifacts in the payload."""
    from lightgbm_tpu.analysis.__main__ import main
    code = main(["--json"])
    payload = json.loads(capsys.readouterr().out)
    assert code == 0 and payload["exit_code"] == 0
    audit_names = {a["name"] for a in payload["audits"]}
    assert {"collective_order", "collective_guarded",
            "collective_observed", "vmem_budget", "hbm_budget",
            "compile_surface", "precision_flow", "transfer",
            "quant_certify", "health_covered"} <= audit_names
    assert payload["lint"]["counts"]["unsuppressed"] == 0
    assert payload["collective_trace"]["findings"] == []
    assert payload["resource_tables"]["vmem"]
    assert payload["compile_surface"]["total_bound"] <= 64
    # the machine-checkable quantization certificate: every spec green,
    # and the int16 histogram bound within the pinned decision budget
    qc = payload["quant_certificate"]
    assert qc["all_ok"]
    hist16 = [c for c in qc["certificates"]
              if c["spec"]["name"].startswith("hist_int16")]
    assert hist16 and all(
        c["bound"] <= qc["budgets"]["split_decision"] for c in hist16)


def test_jg007_skips_imports_sharing_a_line(tmp_path):
    """An import sharing a source line with other code (or a trailing
    comment) is not removable: both the usage count and the fix are
    line-grained, so deleting the line would take the neighbour with
    it (`import os; x = os.path` used to lose the assignment)."""
    pkg = tmp_path / "lightgbm_tpu"
    pkg.mkdir()
    mod = pkg / "mod.py"
    text = ("import os; x = os.path\n"
            "import json  # tooling hook\n"
            "print(x)\n")
    mod.write_text(text)
    cfg = GraftlintConfig(root=str(tmp_path), baseline="baseline.json")
    report = run_lint(config=cfg, autofix=True)
    assert _ids(report.findings, "JG007") == []
    assert report.autofixed == 0
    assert mod.read_text() == text, "autofix touched a shared line"


def test_baseline_rewrites_refuse_filtered_scans(capsys):
    """--prune-baseline / --write-baseline under --rules or path args
    exit 2 without touching the file: a filtered report would mark
    every out-of-scope baseline entry stale and destroy it."""
    from lightgbm_tpu.analysis.__main__ import main
    bl = load_config().baseline_path()
    before = open(bl).read()
    assert main(["--prune-baseline", "--rules", "JG007"]) == 2
    assert main(["lightgbm_tpu/ops", "--prune-baseline"]) == 2
    assert main(["--write-baseline", "--rules", "JG002"]) == 2
    err = capsys.readouterr().err
    assert "full unfiltered scan" in err
    assert open(bl).read() == before


def test_compile_audit_sees_nondecorator_partial_sites():
    """partial(jax.jit, ...) used as an expression (assignment/factory
    form, not a decorator) is the same recompile surface and must be
    enumerated — an unregistered static name there fails the gate."""
    from lightgbm_tpu.analysis.compile_audit import analyze_source
    src = textwrap.dedent("""
        import functools

        import jax

        def body(x, n_iter):
            return x * n_iter

        step = functools.partial(
            jax.jit, static_argnames=("n_iter",))(body)
        """)
    sites = analyze_source(src, "lightgbm_tpu/ops/fixture.py")
    assert [s.kind for s in sites] == ["call"]
    assert sites[0].unbounded == ["n_iter"]


def test_auditor_artifacts_single_pass_matches_fresh():
    """compute_artifacts + run_all(artifacts=...) — the --json CLI's
    single-pass path — must produce the same verdicts and payload as
    fresh per-consumer computation."""
    from lightgbm_tpu.analysis import auditors
    from lightgbm_tpu.analysis import (collective_audit, compile_audit,
                                       resource_audit)
    config = load_config()
    art = auditors.compute_artifacts(config)
    assert set(art) == set(auditors.all_auditors())
    cached = auditors.run_all(config, artifacts=art)
    fresh = auditors.run_all(config)
    assert [(a.name, a.ok, a.detail) for a in cached] \
        == [(a.name, a.ok, a.detail) for a in fresh]
    assert collective_audit.extract_repo_trace(
        config, artifact=art["collective_order"]) \
        == collective_audit.extract_repo_trace(config)
    assert resource_audit.tables(
        config=config, artifact=art["resource_budget"]) \
        == resource_audit.tables(config=config)
    assert compile_audit.compile_surface(
        config, artifact=art["compile_surface"]) \
        == compile_audit.compile_surface(config)
