"""Graft-lint: rule fixtures, engine mechanics, jaxpr audits, self-scan.

Layout mirrors the acceptance criteria:

* every registered JG rule is exercised against seeded-violation
  fixture snippets (positive) and clean twins (negative) — the
  parametrization is driven by the registry, so adding a rule without
  fixtures fails here by construction;
* engine mechanics: inline suppression, skip-file, baseline
  round-trip, unused-import autofix;
* the jaxpr audits run green (the two pinned invariants — no f64
  convert in persist-f32 kernels, serve ladder bound — are tier-1);
* the repo self-scan: ZERO unsuppressed findings, same gate as
  `python -m lightgbm_tpu.analysis`.
"""
import json
import os
import textwrap

import pytest

from lightgbm_tpu.analysis import (GraftlintConfig, load_config, run_audits,
                                   run_lint)
from lightgbm_tpu.analysis.config import _parse_table
from lightgbm_tpu.analysis.lint import (apply_baseline, iter_py_files,
                                        lint_source, load_baseline,
                                        write_baseline)
from lightgbm_tpu.analysis.rules import all_rules

OPS = "lightgbm_tpu/ops/fake.py"          # hot path, kernel-bearing
COLD = "lightgbm_tpu/data/fake.py"        # not a hot path


def _ids(findings, rule=None):
    return [f.rule for f in findings
            if not f.suppressed and (rule is None or f.rule == rule)]


def _lint(src, relpath=OPS, **cfg):
    config = GraftlintConfig(**cfg) if cfg else GraftlintConfig()
    return lint_source(textwrap.dedent(src), relpath, config)


# ---------------------------------------------------------------------------
# per-rule fixtures: positive (fires) + negative (clean twin)
# ---------------------------------------------------------------------------

FIXTURES = {
    "JG001": {
        "positive": """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                if jnp.any(x > 0):
                    return x + 1
                return x
            """,
        "negative": """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x, flag: bool):
                if flag:                       # static python value: fine
                    return x + 1
                return jax.lax.cond(jnp.any(x > 0),
                                    lambda v: v + 1, lambda v: v, x)

            def host(x):
                if jnp.any(x > 0):             # not a jitted scope
                    return 1
                return 0
            """,
    },
    "JG002": {
        "positive": """
            import numpy as np

            def serve(batches, dev):
                out = []
                for b in batches:
                    out.append(np.asarray(dev(b)))     # per-batch sync
                    total = dev(b).sum().item()        # and another
                    scale = float(dev(b)[0])           # and another
                return out, total, scale
            """,
        "negative": """
            import numpy as np

            def serve(batches, dev):
                outs = [dev(b) for b in batches]
                return np.asarray(outs)                # one batched sync
            """,
    },
    "JG003": {
        "positive": """
            import jax.numpy as jnp

            def setup(m):
                pad = jnp.zeros((4, 4))                # f64 under x64
                half = jnp.asarray(0.5)                # f64 under x64
                y = jnp.where(m, 1.0, -1.0)            # f64 select
                return pad, half, y

            def _scan_kernel(hb, cf):
                return jnp.floor(hb * cf + 0.5)        # kernel literal
            """,
        "negative": """
            import jax.numpy as jnp

            def setup(m, x):
                pad = jnp.zeros((4, 4), jnp.float32)
                half = jnp.asarray(0.5, jnp.float32)
                y = jnp.where(m, 1.0, -1.0).astype(x.dtype)
                keep = jnp.where(m, 1.0, x)            # one literal: weak
                return pad, half, y, keep

            def _scan_kernel(hb, cf):
                return jnp.floor(hb * cf + jnp.float32(0.5))

            def host_math(a):
                return a * 0.5                         # not a kernel
            """,
    },
    "JG004": {
        "positive": """
            import jax

            from lightgbm_tpu.ops.pallas_grow import make_level_pass

            def train(trees, step, geo):
                outs = []
                for t in trees:
                    f = jax.jit(step)                  # recompile storm
                    outs.append(f(t))
                return outs

            def grow_levels(levels, geo):
                for lv in levels:
                    lp = make_level_pass(*geo)         # builder per level:
                    lv.run(lp)                         # same storm, hidden
            """,
        "negative": """
            import jax

            from lightgbm_tpu.ops.pallas_grow import make_level_pass

            def train(trees, step):
                f = jax.jit(step)                      # hoisted
                outs = []
                for t in trees:
                    outs.append(f(t))

                def make(c):                           # builder in loop is
                    return jax.jit(lambda x: x + c)    # a def, not a call
                return outs, [make(c) for c in (1, 2)]

            def grow_levels(levels, geo):
                lp = make_level_pass(*geo)             # once per geometry
                for lv in levels:
                    lv.run(lp)
            """,
    },
    "JG005": {
        "positive": """
            import time
            import numpy as np

            def sample(n):
                idx = np.random.permutation(n)         # global RNG
                rng = np.random.default_rng(time.time())   # clock seed
                return idx, rng
            """,
        "negative": """
            import numpy as np

            def sample(n, seed):
                rng = np.random.default_rng(seed)
                return rng.permutation(n), np.random.RandomState(seed)
            """,
    },
    "JG006": {
        "positive": """
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu

            def kernel_call(f, shape):
                return pl.pallas_call(f, out_shape=shape)
            """,
        "negative": """
            from .pallas_compat import HAS_PALLAS, pl, pltpu

            def kernel_call(f, shape):
                if not HAS_PALLAS:
                    return None
                return pl.pallas_call(f, out_shape=shape)
            """,
    },
    "JG007": {
        "positive": """
            import json
            from typing import Dict, List

            def f(d: Dict) -> Dict:
                return d
            """,
        "negative": """
            import json
            from typing import Dict

            try:
                import exotic_backend              # probing idiom: skipped
            except ImportError:
                exotic = None

            import unused_but_marked  # noqa: F401

            def f(d: Dict) -> str:
                return json.dumps(d)
            """,
    },
    # JG008 is scoped to the resilience durability paths; its fixtures
    # carry their own relpath (the "relpath" key overrides the OPS default)
    "JG008": {
        "relpath": "lightgbm_tpu/resilience/fake.py",
        "positive": """
            import json

            def save_state(path, state):
                with open(path, "w") as f:         # in-place: torn on kill
                    json.dump(state, f)
            """,
        "negative": """
            import json
            import os

            def save_state(path, state):
                tmp_path = path + ".tmp"
                with open(tmp_path, "w") as f:
                    json.dump(state, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp_path, path)

            def load_state(path):
                with open(path) as f:              # reads are never flagged
                    return json.load(f)
            """,
    },
}


def test_every_rule_has_fixtures():
    ids = {r.id for r in all_rules()}
    assert ids == set(FIXTURES), "every JG rule needs fixture snippets"
    assert ids == {"JG001", "JG002", "JG003", "JG004", "JG005", "JG006",
                   "JG007", "JG008"}


@pytest.mark.parametrize("rule_id", sorted(FIXTURES))
def test_rule_fires_on_seeded_violation(rule_id):
    rp = FIXTURES[rule_id].get("relpath", OPS)
    hits = _ids(_lint(FIXTURES[rule_id]["positive"], relpath=rp), rule_id)
    assert hits, "%s stayed silent on its seeded violation" % rule_id


@pytest.mark.parametrize("rule_id", sorted(FIXTURES))
def test_rule_silent_on_clean_twin(rule_id):
    rp = FIXTURES[rule_id].get("relpath", OPS)
    hits = _ids(_lint(FIXTURES[rule_id]["negative"], relpath=rp), rule_id)
    assert not hits, "%s false-positived on its clean twin" % rule_id


def test_jg008_outside_scope_is_silent():
    """The same in-place write is fine outside the durability paths (the
    CLI writing a predictions file is not a checkpoint)."""
    hits = _ids(_lint(FIXTURES["JG008"]["positive"], relpath=COLD), "JG008")
    assert hits == []


def test_jg002_fixture_counts_and_cold_path():
    pos = FIXTURES["JG002"]["positive"]
    assert len(_ids(_lint(pos), "JG002")) == 3     # asarray + item + float
    assert _ids(_lint(pos, relpath=COLD), "JG002") == []


def test_jg003_flags_each_shape_once():
    hits = _ids(_lint(FIXTURES["JG003"]["positive"]), "JG003")
    assert len(hits) == 4   # zeros, asarray-literal, where, kernel literal


def test_jg007_fix_wraps_long_from_imports(tmp_path):
    """The rewritten statement must stay valid Python: long from-imports
    wrap in parentheses; plain `import a, b` (no legal paren form) is
    left long rather than broken."""
    import ast as ast_mod

    pkg = tmp_path / "lightgbm_tpu"
    pkg.mkdir()
    mod = pkg / "mod.py"
    mod.write_text(
        "import json, very_long_module_name_aaaa, "
        "very_long_module_name_bbbb, very_long_module_name_cccc\n"
        "from some.rather.deep.package.path import (unused_name_xx, "
        "kept_name_aaaaaaaa, kept_name_bbbbbbbb, kept_name_cccccccc)\n"
        "print(very_long_module_name_aaaa, very_long_module_name_bbbb,\n"
        "      very_long_module_name_cccc, kept_name_aaaaaaaa,\n"
        "      kept_name_bbbbbbbb, kept_name_cccccccc)\n")
    cfg = GraftlintConfig(root=str(tmp_path), baseline="baseline.json")
    report = run_lint(config=cfg, autofix=True)
    assert report.autofixed == 2
    fixed = mod.read_text()
    ast_mod.parse(fixed)                     # must still be valid Python
    assert "json" not in fixed and "unused_name_xx" not in fixed
    from_lines = [ln for ln in fixed.splitlines()
                  if ln.startswith("from ")]
    assert all(len(ln) <= 79 for ln in from_lines), from_lines


def test_write_baseline_keeps_grandfathered(tmp_path):
    """Refreshing the baseline from a report whose findings are already
    baseline-suppressed must re-emit them, not silently drop them (the
    CLI --write-baseline path)."""
    src = """
        import jax.numpy as jnp

        def setup():
            return jnp.zeros((4,))
        """
    findings = _lint(src)
    bl = str(tmp_path / "b.json")
    assert write_baseline(findings, bl) == 1
    again = _lint(src)
    apply_baseline(again, load_baseline(bl))
    assert all(f.suppression == "baseline" for f in again)
    # the refresh the CLI performs: full findings list, suppressed or not
    assert write_baseline(again, bl) == 1
    assert load_baseline(bl)


def test_jg007_fix_rewrites_imports(tmp_path):
    pkg = tmp_path / "lightgbm_tpu"
    pkg.mkdir()
    mod = pkg / "mod.py"
    mod.write_text(textwrap.dedent("""\
        import json
        from typing import Dict, List

        def f(d: Dict) -> Dict:
            return d
        """))
    cfg = GraftlintConfig(root=str(tmp_path), baseline="baseline.json")
    report = run_lint(config=cfg, autofix=True)
    assert report.autofixed == 2
    assert [f for f in report.findings if not f.suppressed] == []
    fixed = mod.read_text()
    assert "import json" not in fixed
    assert "from typing import Dict" in fixed and "List" not in fixed


# ---------------------------------------------------------------------------
# engine mechanics
# ---------------------------------------------------------------------------

def test_inline_suppression_same_line_and_above():
    src = """
        import jax.numpy as jnp

        def setup():
            a = jnp.zeros((4,))  # graftlint: disable=JG003
            # graftlint: disable=JG003
            b = jnp.zeros((4,))
            c = jnp.zeros((4,))
            return a, b, c
        """
    fs = [f for f in _lint(src) if f.rule == "JG003"]
    assert [f.suppressed for f in fs] == [True, True, False]
    assert {f.suppression for f in fs if f.suppressed} == {"inline"}


def test_skip_file_marker():
    src = "# graftlint: skip-file\nimport jax.numpy as jnp\n" \
          "bad = jnp.zeros((4,))\n"
    assert lint_source(src, OPS, GraftlintConfig()) == []


def test_baseline_roundtrip(tmp_path):
    src = """
        import jax.numpy as jnp

        def setup():
            a = jnp.zeros((4,))
            return a, jnp.zeros((8,))
        """
    findings = _lint(src)
    assert len(_ids(findings)) == 2
    bl_path = str(tmp_path / "baseline.json")
    assert write_baseline(findings, bl_path) == 2
    baseline = load_baseline(bl_path)
    fresh = _lint(src)
    apply_baseline(fresh, baseline)
    assert _ids(fresh) == []
    assert all(f.suppression == "baseline" for f in fresh)
    # baseline matches by source line, not line number: new unrelated
    # findings stay unsuppressed
    grown = _lint(src.rstrip() + "\n\n        more = jnp.zeros((2,))\n")
    apply_baseline(grown, baseline)
    assert len(_ids(grown)) == 1


def test_config_table_parsing():
    table = _parse_table(textwrap.dedent("""\
        [tool.other]
        x = 1
        [tool.graftlint]
        include = ["lightgbm_tpu"]
        exclude = [
            "__pycache__",
            "native",
        ]
        baseline = "b.json"
        disable = []
        [tool.after]
        y = 2
        """))
    assert table["include"] == ["lightgbm_tpu"]
    assert table["exclude"] == ["__pycache__", "native"]
    assert table["baseline"] == "b.json"
    assert table["disable"] == []


def test_repo_config_loads_and_walks():
    cfg = load_config()
    files = iter_py_files(cfg)
    assert any(p.endswith("ops/pallas_scan.py") for p in files)
    assert not any("__pycache__" in p for p in files)
    assert cfg.is_hot_path("lightgbm_tpu/ops/grow.py")
    assert not cfg.is_hot_path("lightgbm_tpu/data/dataset.py")


# ---------------------------------------------------------------------------
# jaxpr audits (the two pinned invariants are tier-1 here)
# ---------------------------------------------------------------------------

def test_audits_all_green():
    results = {r.name: r for r in run_audits()}
    assert set(results) == {
        "hist_window_f32", "scan_pair_f32", "scan_blocks_f32",
        "persist_split_pass", "persist_level_pass",
        "predict_traversal_f32", "predict_donation",
        "serve_ladder_bound"}
    bad = {n: r.detail for n, r in results.items() if not r.ok}
    assert not bad, bad


def test_audit_catches_f64_convert():
    """The f64 detector actually detects: a deliberately-widening
    program must fail the same check the kernels pass."""
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.analysis.jaxpr_audit import find_f64_converts

    def leaky(x):
        return x.astype(jnp.float64) * 2.0

    closed = jax.make_jaxpr(leaky)(
        jax.ShapeDtypeStruct((8,), jnp.float32))
    assert find_f64_converts(closed.jaxpr)


def test_audit_catches_callback_in_loop():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from lightgbm_tpu.analysis.jaxpr_audit import find_host_prims_in_loops

    def bad(x):
        def body(_, v):
            return v + jax.pure_callback(
                lambda a: np.asarray(a), jax.ShapeDtypeStruct((), v.dtype),
                v[0])
        return jax.lax.fori_loop(0, 3, body, x)

    closed = jax.make_jaxpr(bad)(jax.ShapeDtypeStruct((4,), jnp.float32))
    assert find_host_prims_in_loops(closed.jaxpr)


# ---------------------------------------------------------------------------
# the gate: repo self-scan
# ---------------------------------------------------------------------------

def test_repo_self_scan_clean():
    """`python -m lightgbm_tpu.analysis` must exit 0: zero unsuppressed
    findings over the whole package (baseline-suppressed grandfathered
    ones are allowed, parse errors are not)."""
    report = run_lint()
    assert report.parse_errors == []
    bad = [(f.path, f.line, f.rule, f.message)
           for f in report.unsuppressed]
    assert not bad, "unsuppressed graft-lint findings:\n%s" % \
        "\n".join("%s:%d %s %s" % b for b in bad)
    assert report.files_scanned > 60


def test_baseline_only_contains_known_grandfathered():
    """The baseline must shrink, never grow: pin its current contents so
    a PR that adds entries has to justify itself here."""
    cfg = load_config()
    with open(cfg.baseline_path()) as f:
        data = json.load(f)
    by_rule = {}
    for ent in data["findings"]:
        by_rule.setdefault(ent["rule"], 0)
        by_rule[ent["rule"]] += ent["count"]
    assert set(by_rule) <= {"JG002"}, by_rule
    assert sum(by_rule.values()) <= 9, by_rule


def test_lint_lands_on_telemetry_counters():
    """Findings/files land on `analysis::*` counters when telemetry is
    on, so services embedding the gate see lint drift next to their
    perf counters."""
    from lightgbm_tpu.telemetry import events

    prev = events.mode()
    events.enable("timers")
    events.reset()
    try:
        run_lint(paths=["lightgbm_tpu/analysis/lint.py"])
        counts = events.counts_snapshot()
        assert counts.get("analysis::files_scanned", 0) == 1
        assert "analysis::findings" in counts
    finally:
        events.reset()
        if prev == events.OFF:
            events.disable()


def test_cli_smoke(capsys):
    from lightgbm_tpu.analysis.__main__ import main
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "JG001" in out and "JG007" in out
    # lint-only over one file: exits 0 and prints the summary line
    assert main(["lightgbm_tpu/analysis/lint.py", "--no-audit"]) == 0
    assert "graft-lint:" in capsys.readouterr().out
