"""Fused Pallas split-scan kernel vs the XLA scan: same trees.

The analog of the reference's GPU_DEBUG_COMPARE self-check
(src/treelearner/gpu_tree_learner.cpp:993-1030) for the split-scan kernel
(ops/pallas_scan.py): grow whole trees with scan_impl="pallas" (interpreter
mode on CPU) and scan_impl="xla" at identical f32 settings and require the
same structure (features, thresholds, default directions) and matching
leaf values/gains to f32 reassociation tolerance.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.data.dataset import BinnedDataset
from lightgbm_tpu.ops.grow import grow_tree, grow_tree_partitioned
from lightgbm_tpu.ops.pallas_scan import HAS_PALLAS
from lightgbm_tpu.ops.split import SplitParams
from lightgbm_tpu.treelearner.serial import (build_cat_layout,
                                             build_gw_global)

if not HAS_PALLAS:  # pragma: no cover
    pytest.skip("pallas unavailable", allow_module_level=True)


def _problem(n=4000, f=7, seed=3, missing=True):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    if missing:
        X[rng.random((n, f)) < 0.08] = np.nan       # NaN missing type
        X[:, 2] = np.where(rng.random(n) < 0.6, 0.0, X[:, 2])  # zero-heavy
    y = (np.nan_to_num(X[:, 0]) + 0.5 * np.nan_to_num(X[:, 1]) > 0.2)
    cfg = lgb.Config({"num_leaves": 31, "max_bin": 63,
                      "min_data_in_leaf": 20, "zero_as_missing": False})
    ds = BinnedDataset.from_matrix(X, cfg, label=y.astype(np.float32))
    grad = jnp.asarray((0.5 - y).astype(np.float32))
    hess = jnp.full(n, 0.25, jnp.float32)
    return cfg, ds, grad, hess


def _grow(ds, cfg, grad, hess, scan_impl, partitioned):
    from lightgbm_tpu.ops.grow import GrowConfig
    n = ds.num_data
    layout, meta = ds.to_device(cfg)
    widths = ds.bin_end - ds.bin_start
    gc = GrowConfig(
        num_leaves=31, total_bins=ds.total_bins,
        num_features=ds.num_features, use_mc=False, max_depth=-1,
        rows_per_chunk=0, cat_width=1, hist_impl="scatter",
        scan_width=int(widths.max()), use_dp=False, window_chunk=512,
        hist_dtype="f32", use_l1=False, use_mds=False,
        scan_impl=scan_impl)
    params = SplitParams.from_config(cfg)
    fmask = jnp.ones(ds.num_features, bool)
    bag = jnp.ones(n, bool)
    cat = build_cat_layout(ds, 1)
    if partitioned:
        arrays, _ = grow_tree_partitioned(
            layout, grad, hess, bag, meta, params, fmask, ds.fix_info(),
            gc, gw_global=build_gw_global(ds), cat=cat)
    else:
        arrays, _ = grow_tree(layout, grad, hess, bag, meta, params,
                              fmask, ds.fix_info(), gc, cat=cat)
    import jax
    return jax.device_get(arrays)


@pytest.mark.parametrize("partitioned", [False, True])
@pytest.mark.parametrize("missing", [False, True])
def test_pallas_scan_matches_xla(partitioned, missing):
    cfg, ds, grad, hess = _problem(missing=missing)
    a = _grow(ds, cfg, grad, hess, "xla", partitioned)
    b = _grow(ds, cfg, grad, hess, "pallas", partitioned)
    assert a.num_leaves == b.num_leaves
    k = int(a.num_leaves) - 1
    np.testing.assert_array_equal(a.split_feature[:k], b.split_feature[:k])
    np.testing.assert_array_equal(a.threshold[:k], b.threshold[:k])
    np.testing.assert_array_equal(a.default_left[:k], b.default_left[:k])
    np.testing.assert_array_equal(a.split_leaf[:k], b.split_leaf[:k])
    np.testing.assert_allclose(a.gain[:k], b.gain[:k], rtol=2e-4, atol=1e-5)
    nl = int(a.num_leaves)
    np.testing.assert_array_equal(a.leaf_count[:nl], b.leaf_count[:nl])
    np.testing.assert_allclose(a.leaf_value[:nl], b.leaf_value[:nl],
                               rtol=2e-4, atol=1e-7)
    np.testing.assert_array_equal(a.row_leaf, b.row_leaf)


def test_pallas_scan_used_on_default_config_shapes():
    """resolve_scan_impl must pick the kernel exactly for the fast path."""
    from lightgbm_tpu.treelearner.serial import resolve_scan_impl
    base = dict(use_dp=False, use_mc=False, use_l1=False, use_mds=False,
                extra_trees=False, bynode_k=0, use_cegb=False,
                num_features=28, scan_width=256)
    cfg = lgb.Config({})
    # CPU backend in tests -> xla even for the fast path
    assert resolve_scan_impl(cfg, dict(base)) == "xla"
    cfg2 = lgb.Config({"tpu_scan_impl": "pallas"})
    # explicit pallas on a non-fast config warns and falls back
    assert resolve_scan_impl(cfg2, dict(base, use_mc=True)) == "xla"
