"""Dataset file IO: path-input Datasets, binary cache, two_round streaming.

Mirrors the reference's dataset-loading surface: LGBM_DatasetCreateFromFile
(path input), save_binary + LoadFromBinFile (cache round trip must produce
bit-identical binned matrices and therefore identical models), and
two_round chunked loading (same dataset as one-round up to the row sample).
"""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.data.dataset import BinnedDataset


def _write_tsv(path, n=3000, f=6, seed=0, header=False):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    X[rng.random((n, f)) < 0.05] = np.nan
    y = (X[:, 0] > 0).astype(float)
    data = np.column_stack([y, np.nan_to_num(X, nan=np.nan)])
    lines = []
    if header:
        lines.append("\t".join(["label"] + ["f%d" % i for i in range(f)]))
    for row in data:
        lines.append("\t".join("nan" if np.isnan(v) else "%.8g" % v
                               for v in row))
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    return X, y


def test_path_dataset_and_binary_cache_roundtrip(tmp_path):
    p = str(tmp_path / "train.tsv")
    X, y = _write_tsv(p)
    ds1 = lgb.Dataset(p, params={"save_binary": True, "max_bin": 63})
    ds1.construct()
    assert os.path.exists(p + ".bin")

    ds2 = lgb.Dataset(p + ".bin", params={"max_bin": 63})
    ds2.construct()
    a, b = ds1._inner, ds2._inner
    np.testing.assert_array_equal(a.binned, b.binned)
    np.testing.assert_array_equal(a.metadata.label, b.metadata.label)
    assert a.total_bins == b.total_bins
    assert a.groups == b.groups
    for ma, mb in zip(a.bin_mappers, b.bin_mappers):
        np.testing.assert_array_equal(ma.bin_upper_bound, mb.bin_upper_bound)

    # identical models from text and binary datasets
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "max_bin": 63}
    b1 = lgb.train(dict(params), lgb.Dataset(p, params={"max_bin": 63}), 5,
                   verbose_eval=False)
    b2 = lgb.train(dict(params), ds2, 5, verbose_eval=False)
    np.testing.assert_array_equal(
        b1.predict(np.nan_to_num(X[:100])),
        b2.predict(np.nan_to_num(X[:100])))


def test_two_round_matches_one_round(tmp_path):
    p = str(tmp_path / "train.tsv")
    _write_tsv(p, n=2500)
    cfg = lgb.Config({"max_bin": 63})
    one = lgb.Dataset(p, params={"max_bin": 63})
    one.construct()
    two = BinnedDataset.from_text_two_round(p, cfg)
    # sample row count <= bin_construct_sample_cnt covers all 2500 rows, so
    # both rounds see the same sample and must produce the same dataset
    np.testing.assert_array_equal(one._inner.binned, two.binned)
    np.testing.assert_array_equal(one._inner.metadata.label,
                                  two.metadata.label)
    assert one._inner.total_bins == two.total_bins


def test_two_round_param_via_dataset(tmp_path):
    p = str(tmp_path / "train.tsv")
    X, y = _write_tsv(p, n=2000)
    ds = lgb.Dataset(p, params={"two_round": True, "max_bin": 63})
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "max_bin": 63}, ds, 5,
                    verbose_eval=False)
    pr = bst.predict(np.nan_to_num(X))
    assert (((pr > 0.5) == y).mean()) > 0.8


def test_native_binning_matches_numpy():
    """C++ binning kernel (native/binrows.cpp) must reproduce the numpy
    path bit-for-bit across NaN/zero/categorical/EFB-bundled features."""
    rng = np.random.default_rng(0)
    n, f = 30000, 12
    X = rng.normal(size=(n, f))
    X[rng.random((n, f)) < 0.1] = np.nan
    X[:, 3] = np.where(rng.random(n) < 0.7, 0.0, X[:, 3])
    X[:, 7] = rng.integers(0, 12, n)
    X[:, 8] = (rng.random(n) < 0.05) * rng.integers(1, 5, n)
    X[:, 9] = (rng.random(n) < 0.05) * rng.integers(1, 5, n)
    cfg = lgb.Config({"max_bin": 255})
    ds = BinnedDataset.from_matrix(
        X, cfg, categorical_features=[7],
        label=(np.nan_to_num(X[:, 0]) > 0).astype(np.float32))
    out_np = np.zeros_like(ds.binned)
    native = ds._bin_rows_native
    ds._bin_rows_native = lambda X, out: False   # force the numpy path
    ds._bin_rows(X, out_np)
    ds._bin_rows_native = native
    out_c = np.zeros_like(ds.binned)
    if not ds._bin_rows_native(X, out_c):
        pytest.skip("native toolchain unavailable")
    np.testing.assert_array_equal(out_np, out_c)


def test_add_features_from():
    """Dataset.add_features_from (Dataset::AddFeaturesFrom,
    src/io/dataset.cpp:1465): merged dataset must train identically to
    binning the concatenated matrix in one shot when grouping is disabled
    (EFB may bundle across the halves otherwise)."""
    rng = np.random.default_rng(5)
    n = 1200
    Xa = rng.normal(size=(n, 3))
    Xb = rng.normal(size=(n, 2))
    y = (Xa[:, 0] + Xb[:, 0] > 0).astype(float)
    params = {"max_bin": 63, "enable_bundle": False, "verbosity": -1}
    da = lgb.Dataset(Xa, y, params=dict(params), free_raw_data=False)
    db = lgb.Dataset(Xb, params=dict(params), free_raw_data=False)
    da.construct()
    db.construct()
    da.add_features_from(db)
    assert da.num_feature() == 5
    dc = lgb.Dataset(np.concatenate([Xa, Xb], axis=1), y,
                     params=dict(params), free_raw_data=False)
    dc.construct()
    np.testing.assert_array_equal(da._inner.binned, dc._inner.binned)
    tp = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "max_bin": 63, "enable_bundle": False}
    b1 = lgb.train(dict(tp), da, 5, verbose_eval=False)
    b2 = lgb.train(dict(tp), dc, 5, verbose_eval=False)
    X = np.concatenate([Xa, Xb], axis=1)
    np.testing.assert_array_equal(b1.predict(X), b2.predict(X))


@pytest.mark.slow  # tier-1 870s budget: cheaper sibling tests cover this area
def test_cli_save_binary_then_retrain(tmp_path):
    import subprocess
    import sys
    p = str(tmp_path / "t.tsv")
    _write_tsv(p, n=1500)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    m1 = str(tmp_path / "m1.txt")

    def run(*args):
        r = subprocess.run([sys.executable, "-m", "lightgbm_tpu"]
                           + list(args), env=env, capture_output=True,
                           text=True, cwd="/root/repo")
        assert r.returncode == 0, r.stderr[-1500:]

    run("task=train", "data=" + p, "objective=binary", "num_iterations=3",
        "save_binary=true", "output_model=" + m1, "max_bin=63")
    assert os.path.exists(p + ".bin")
    m2 = str(tmp_path / "m2.txt")
    run("task=train", "data=" + p + ".bin", "objective=binary",
        "num_iterations=3", "output_model=" + m2, "max_bin=63")
    t1 = open(m1).read().split("parameters:")[0]
    t2 = open(m2).read().split("parameters:")[0]
    assert t1 == t2  # same model from text and binary-cache input


def test_native_binning_matches_numpy_adversarial():
    """The native grid-LUT accelerated binning (native/binrows.cpp) is
    bit-identical to the numpy searchsorted fallback on adversarial value
    distributions (extreme outliers, boundary ties, constants, skew,
    sparse zeros, NaN)."""
    import lightgbm_tpu as lgb
    import lightgbm_tpu.data.dataset as D
    rng = np.random.default_rng(0)
    n = 60000
    X = np.column_stack([
        rng.standard_cauchy(n) * 1e6,
        np.round(rng.normal(size=n), 1),
        np.full(n, 3.14),
        rng.exponential(size=n) ** 3,
        np.where(rng.random(n) < 0.95, 0.0, rng.normal(size=n)),
    ])
    X[::13, 0] = np.nan
    y = (rng.random(n) > 0.5).astype(float)
    d1 = lgb.Dataset(X, y)
    d1.construct()
    b_native = np.asarray(d1._inner.binned).copy()
    orig = D.BinnedDataset._bin_rows_native
    try:
        D.BinnedDataset._bin_rows_native = lambda self, X, out: False
        d2 = lgb.Dataset(X, y)
        d2.construct()
        b_np = np.asarray(d2._inner.binned)
    finally:
        D.BinnedDataset._bin_rows_native = orig
    assert np.array_equal(b_native, b_np)
