"""convert_model / Tree::ToIfElse codegen: the generated standalone C++
must COMPILE and reproduce Booster.predict exactly (the strongest possible
check of the if-else emission — reference analog: SaveModelToIfElse,
src/boosting/gbdt_model_text.cpp:276 + src/io/tree.cpp:383)."""
import ctypes
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _compile(src_path, out_path):
    try:
        r = subprocess.run(["g++", "-O1", "-shared", "-fPIC", "-std=c++17",
                            src_path, "-o", out_path],
                           capture_output=True, timeout=300, text=True)
    except OSError:
        pytest.skip("C++ toolchain unavailable")
    assert r.returncode == 0, "generated code failed to compile:\n" \
        + r.stderr[:2000]


def _check_model(bst, X, tmp_path, tag, n_out=1, proba=None):
    src = str(tmp_path / ("conv_%s.cpp" % tag))
    lib_path = str(tmp_path / ("conv_%s.so" % tag))
    with open(src, "w") as f:
        f.write(bst._booster.model_to_if_else())
    _compile(src, lib_path)
    lib = ctypes.CDLL(lib_path)
    out = np.zeros(n_out)
    raws = np.zeros((len(X), n_out))
    preds = np.zeros((len(X), n_out))
    for i, row in enumerate(np.ascontiguousarray(X, dtype=np.float64)):
        lib.PredictRaw(row.ctypes.data_as(ctypes.c_void_p),
                       out.ctypes.data_as(ctypes.c_void_p))
        raws[i] = out
        lib.Predict(row.ctypes.data_as(ctypes.c_void_p),
                    out.ctypes.data_as(ctypes.c_void_p))
        preds[i] = out
    ref_raw = bst.predict(X, raw_score=True)
    np.testing.assert_allclose(raws.reshape(ref_raw.shape), ref_raw,
                               rtol=1e-12, atol=1e-12)
    if proba is not None:
        np.testing.assert_allclose(preds.reshape(proba.shape), proba,
                                   rtol=1e-9, atol=1e-12)


def test_convert_binary_with_missing(tmp_path):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(600, 5))
    X[rng.random(X.shape) < 0.1] = np.nan
    y = (np.nan_to_num(X[:, 0]) + 0.4 * np.nan_to_num(X[:, 1]) > 0)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1},
                    lgb.Dataset(X, y.astype(float)), 8, verbose_eval=False)
    _check_model(bst, X, tmp_path, "bin", proba=bst.predict(X))


@pytest.mark.slow  # tier-1 870s budget: cheaper sibling tests cover this area
def test_convert_multiclass_and_categorical(tmp_path):
    rng = np.random.default_rng(1)
    n = 800
    cat = rng.integers(0, 7, n).astype(float)
    X = np.column_stack([rng.normal(size=(n, 3)), cat])
    y = (np.digitize(X[:, 0], [-0.5, 0.5]) + (cat == 3)).clip(0, 2)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "verbosity": -1},
                    lgb.Dataset(X, y, categorical_feature=[3]), 5,
                    verbose_eval=False)
    _check_model(bst, X, tmp_path, "mc", n_out=3, proba=bst.predict(X))


def test_convert_categorical_nan_and_negative_inputs(tmp_path):
    """Categorical routing edge cases must match Booster.predict: NaN acts
    as category 0 when missing_type != NaN, and fractional negatives in
    (-1, 0) go right even though integer truncation maps them to 0."""
    rng = np.random.default_rng(5)
    n = 500
    cat = rng.integers(0, 6, n).astype(float)   # no NaN at train time
    X = np.column_stack([cat, rng.normal(size=n)])
    y = np.isin(cat, [0, 2, 4]).astype(float)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1, "min_data_in_leaf": 1},
                    lgb.Dataset(X, y, categorical_feature=[0]), 6,
                    verbose_eval=False)
    X_edge = np.array([[np.nan, 0.0], [-0.5, 0.0], [-7.0, 0.0],
                       [0.0, 0.0], [2.0, 0.0], [99.0, 0.0]])
    _check_model(bst, X_edge, tmp_path, "catedge",
                 proba=bst.predict(X_edge))


def test_convert_cli_roundtrip(tmp_path):
    rng = np.random.default_rng(2)
    X = rng.normal(size=(400, 4))
    y = X[:, 0] * 2 + 0.1 * rng.normal(size=400)
    model_path = str(tmp_path / "m.txt")
    lgb.train({"objective": "regression", "num_leaves": 7,
               "verbosity": -1}, lgb.Dataset(X, y), 5,
              verbose_eval=False).save_model(model_path)
    out_cpp = str(tmp_path / "model.cpp")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-m", "lightgbm_tpu",
                        "task=convert_model", "input_model=" + model_path,
                        "convert_model=" + out_cpp],
                       env=env, capture_output=True, text=True,
                       cwd="/root/repo")
    assert r.returncode == 0, r.stderr[-800:]
    assert os.path.exists(out_cpp)
    assert "PredictTree0" in open(out_cpp).read()
