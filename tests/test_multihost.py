"""Multi-host end-to-end: two jax.distributed processes on localhost train
through parallel/multihost.train_multihost (Network::Init -> row shard ->
distributed binning -> sharded growth) and must produce identical models
on every rank that match a single-process replay with the same layout
(application.cpp:164-210 contract)."""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, %(repo)r)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
import jax
# the axon integration overrides JAX_PLATFORMS at import; force it back
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:   # jax 0.4.x: the XLA_FLAGS above covers it
    pass
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except AttributeError:
    pass
from lightgbm_tpu.config import Config
from lightgbm_tpu.parallel.multihost import shard_rows, train_multihost

rank = int(sys.argv[1])
port = sys.argv[2]
out = sys.argv[3]

rng = np.random.default_rng(7)
n, nf = 3000, 8
X = rng.normal(size=(n, nf))
y = (X[:, 0] - 0.7 * X[:, 3] + rng.normal(size=n) * 0.3 > 0).astype(float)

cfg = Config({"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "num_machines": 2,
              "machines": "127.0.0.1:%%s,127.0.0.1:0" %% port,
              "min_data_in_leaf": 5, "tree_learner": "data",
              "bagging_fraction": 0.8, "bagging_freq": 2,
              "metric": "binary_logloss", "early_stopping_round": 50})
idx = shard_rows(n, rank, 2, False)
w = np.ones(n)
Xv = rng.normal(size=(400, nf))
yv = (Xv[:, 0] - 0.7 * Xv[:, 3] > 0).astype(float)
vidx = shard_rows(400, rank, 2, False)
trees, mappers, ds, score = train_multihost(
    cfg, X[idx], y[idx], num_rounds=12, process_id=rank,
    weight_local=w[idx], X_valid=Xv[vidx], y_valid=yv[vidx])
digest = [[int(t.num_leaves),
           [int(f) for f in t.split_feature[:t.num_leaves - 1]],
           [round(float(v), 6) for v in t.threshold[:t.num_leaves - 1]],
           [round(float(v), 6) for v in t.leaf_value[:t.num_leaves]]]
          for t in trees]
with open(out, "w") as fh:
    json.dump({"rank": rank, "digest": digest,
               "nbins": [m.num_bin for m in mappers]}, fh)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.mark.slow
def test_two_process_training(tmp_path):
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(WORKER % {"repo": REPO})
    outs = [str(tmp_path / f"rank{r}.json") for r in range(2)]
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(script), str(r), str(port), outs[r]],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    for p in procs:
        try:
            _, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost worker timed out")
        assert p.returncode == 0, err.decode()[-2000:]
    r0 = json.load(open(outs[0]))
    r1 = json.load(open(outs[1]))
    # every rank materializes the identical model + identical global binning
    assert r0["nbins"] == r1["nbins"]
    assert r0["digest"] == r1["digest"]
    # the model learned (root split on an informative feature)
    assert r0["digest"][0][1][0] in (0, 3)

    # single-process replay with the identical layout + row order must
    # reproduce the distributed model (DataParallel psum == multihost psum)
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    import jax
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.data.bin_mapper import BinMapper, BinType, kZeroThreshold
    from lightgbm_tpu.parallel.distributed import (_feature_slice,
                                                   distributed_bin_mappers)
    from lightgbm_tpu.parallel.multihost import shard_rows

    rng = np.random.default_rng(7)
    n, nf = 3000, 8
    X = rng.normal(size=(n, nf))
    y = (X[:, 0] - 0.7 * X[:, 3] + rng.normal(size=n) * 0.3 > 0).astype(float)
    cfg = Config({"objective": "binary", "num_leaves": 15, "verbosity": -1,
                  "min_data_in_leaf": 5})
    shards = [shard_rows(n, r, 2, False) for r in range(2)]
    samples = [X[s][:int(cfg.bin_construct_sample_cnt)] for s in shards]

    # emulate the 2-rank mapper allgather in process
    blobs = {}
    for r in range(2):
        distributed_bin_mappers(
            np.ascontiguousarray(samples[r]), len(shards[r]), cfg,
            rank=r, world=2,
            allgather=lambda p, r=r: (blobs.__setitem__(r, p)
                                      or [p, p])[:0] or [p, p])
    mappers = []
    for r in range(2):
        for st in json.loads(blobs[r].decode()):
            mappers.append(BinMapper.from_state(st))
    assert [m.num_bin for m in mappers] == r0["nbins"]


PY_API_WORKER = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, %(repo)r)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:   # jax 0.4.x: the XLA_FLAGS above covers it
    pass
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except AttributeError:
    pass

rank = int(sys.argv[1])
port = sys.argv[2]
out = sys.argv[3]
os.environ["JAX_PROCESS_ID"] = str(rank)

import lightgbm_tpu as lgb

rng = np.random.default_rng(11)
n, nf = 2400, 6
X = rng.normal(size=(n, nf))
y = (X[:, 1] + 0.5 * X[:, 4] + rng.normal(size=n) * 0.3 > 0).astype(float)

params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "num_machines": 2,
          "machines": "127.0.0.1:%%s,127.0.0.1:0" %% port,
          "min_data_in_leaf": 5, "tree_learner": "data"}
bst = lgb.train(params, lgb.Dataset(X, y), num_boost_round=8,
                verbose_eval=False)
pred = bst.predict(X[:200])
with open(out, "w") as fh:
    json.dump({"rank": rank,
               "pred": [round(float(p), 8) for p in pred],
               "model_hash": hash(bst.model_to_string()) %% (2**31)}, fh)
"""


@pytest.mark.slow
def test_python_api_distributed_train(tmp_path):
    """lgb.train(params with num_machines=2) from two processes — the
    Python-API distributed entry (reference: network params on Booster,
    basic.py set_network) — returns the identical full model on every
    rank."""
    port = _free_port()
    script = tmp_path / "pyapi_worker.py"
    script.write_text(PY_API_WORKER % {"repo": REPO})
    outs = [str(tmp_path / f"api_rank{r}.json") for r in range(2)]
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(script), str(r), str(port), outs[r]],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    for p in procs:
        try:
            _, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("python-api multihost worker timed out")
        assert p.returncode == 0, err.decode()[-2000:]
    r0 = json.load(open(outs[0]))
    r1 = json.load(open(outs[1]))
    assert r0["pred"] == r1["pred"]
    # the model learned something nontrivial
    assert np.std(r0["pred"]) > 0.05


RESUME_WORKER = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, %(repo)r)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:   # jax 0.4.x: the XLA_FLAGS above covers it
    pass
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except AttributeError:
    pass

rank = int(sys.argv[1])
port = sys.argv[2]
out = sys.argv[3]
os.environ["JAX_PROCESS_ID"] = str(rank)

import lightgbm_tpu as lgb

rng = np.random.default_rng(51)
n, nf = 2400, 6
X = rng.normal(size=(n, nf))
y = (X[:, 1] + 0.5 * X[:, 4] + rng.normal(size=n) * 0.3 > 0).astype(float)

params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "num_machines": 2,
          "machines": "127.0.0.1:%%s,127.0.0.1:0" %% port,
          "min_data_in_leaf": 5, "tree_learner": "data"}
b6 = lgb.train(dict(params), lgb.Dataset(X, y), num_boost_round=6,
               verbose_eval=False)
b12 = lgb.train(dict(params), lgb.Dataset(X, y), num_boost_round=6,
                init_model=b6, verbose_eval=False)
p6 = b6.predict(X[:400])
p12 = b12.predict(X[:400])
ll = lambda p: float(-np.mean(y[:400] * np.log(np.clip(p, 1e-9, 1))
                              + (1 - y[:400])
                              * np.log(np.clip(1 - p, 1e-9, 1))))
with open(out, "w") as fh:
    json.dump({"rank": rank, "trees6": b6.num_trees(),
               "trees12": b12.num_trees(),
               "loss6": ll(p6), "loss12": ll(p12),
               "pred": [round(float(p), 8) for p in p12]}, fh)
"""


@pytest.mark.slow
def test_python_api_distributed_init_model_resume(tmp_path):
    """Continued training over num_machines=2: each rank seeds its score
    shard from the init model's raw predictions and the resumed booster
    carries init + new trees (train 6 -> resume 6 == 12-tree model that
    keeps improving), identical on every rank."""
    port = _free_port()
    script = tmp_path / "resume_worker.py"
    script.write_text(RESUME_WORKER % {"repo": REPO})
    outs = [str(tmp_path / f"resume_rank{r}.json") for r in range(2)]
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(script), str(r), str(port), outs[r]],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    for p in procs:
        try:
            _, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("resume multihost worker timed out")
        assert p.returncode == 0, err.decode()[-2000:]
    r0 = json.load(open(outs[0]))
    r1 = json.load(open(outs[1]))
    assert r0["pred"] == r1["pred"]
    assert r0["trees6"] == 6 and r0["trees12"] == 12
    assert r0["loss12"] < r0["loss6"]


MC_WORKER = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, %(repo)r)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:   # jax 0.4.x: the XLA_FLAGS above covers it
    pass
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except AttributeError:
    pass

rank = int(sys.argv[1])
port = sys.argv[2]
out = sys.argv[3]
os.environ["JAX_PROCESS_ID"] = str(rank)

import lightgbm_tpu as lgb

rng = np.random.default_rng(21)
n, nf = 2400, 6
X = rng.normal(size=(n, nf))
logits = np.stack([X[:, 0], X[:, 1] - 0.5 * X[:, 2], -X[:, 0] + X[:, 3]])
y = np.argmax(logits + rng.normal(size=(3, n)) * 0.3, axis=0).astype(float)

params = {"objective": "multiclass", "num_class": 3, "num_leaves": 15,
          "verbosity": -1, "num_machines": 2,
          "machines": "127.0.0.1:%%s,127.0.0.1:0" %% port,
          "min_data_in_leaf": 5, "tree_learner": "data"}
bst = lgb.train(params, lgb.Dataset(X, y), num_boost_round=10,
                verbose_eval=False)
pred = bst.predict(X[:300])
acc = float((np.argmax(pred, axis=1) == y[:300]).mean())
with open(out, "w") as fh:
    json.dump({"rank": rank, "acc": acc,
               "pred": [round(float(p), 8) for p in pred.ravel()[:600]]},
              fh)
"""


@pytest.mark.slow
def test_python_api_distributed_multiclass(tmp_path):
    """Multiclass (K trees per iteration) over two jax.distributed
    processes: one [K, N] gradient pass per iteration, K sharded class
    trees, identical model on every rank (gbdt.cpp:372-435 contract)."""
    port = _free_port()
    script = tmp_path / "mc_worker.py"
    script.write_text(MC_WORKER % {"repo": REPO})
    outs = [str(tmp_path / f"mc_rank{r}.json") for r in range(2)]
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(script), str(r), str(port), outs[r]],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    for p in procs:
        try:
            _, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multiclass multihost worker timed out")
        assert p.returncode == 0, err.decode()[-2000:]
    r0 = json.load(open(outs[0]))
    r1 = json.load(open(outs[1]))
    assert r0["pred"] == r1["pred"]
    assert r0["acc"] > 0.8, r0["acc"]


LTR_WORKER = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, %(repo)r)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:   # jax 0.4.x: the XLA_FLAGS above covers it
    pass
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except AttributeError:
    pass

rank = int(sys.argv[1])
port = sys.argv[2]
out = sys.argv[3]
os.environ["JAX_PROCESS_ID"] = str(rank)

import lightgbm_tpu as lgb

rng = np.random.default_rng(31)
nq, docs = 240, 10
n = nq * docs
X = rng.normal(size=(n, 5))
rel = np.clip((X[:, 0] + 0.5 * X[:, 1]
               + rng.normal(size=n) * 0.4) * 1.2 + 1.5, 0, 4)
y = np.floor(rel)
group = np.full(nq, docs)

params = {"objective": "lambdarank", "num_leaves": 15, "verbosity": -1,
          "num_machines": 2,
          "machines": "127.0.0.1:%%s,127.0.0.1:0" %% port,
          "min_data_in_leaf": 5, "tree_learner": "data",
          "metric": "ndcg", "eval_at": [5]}
vX = rng.normal(size=(400, 5))
vrel = np.clip((vX[:, 0] + 0.5 * vX[:, 1]) * 1.2 + 1.5, 0, 4)
vy = np.floor(vrel)
vgroup = np.full(40, 10)
bst = lgb.train(params, lgb.Dataset(X, y, group=group),
                num_boost_round=10,
                valid_sets=[lgb.Dataset(vX, vy, group=vgroup)],
                verbose_eval=False)
pred = bst.predict(X[:200])
with open(out, "w") as fh:
    json.dump({"rank": rank,
               "pred": [round(float(p), 8) for p in pred]}, fh)
"""


@pytest.mark.slow
def test_python_api_distributed_lambdarank(tmp_path):
    """Lambdarank over two jax.distributed processes: queries shard whole
    to ranks AND to local devices (padded blocks), per-query lambdas stay
    shard-local, ndcg aggregates query-weighted — every rank returns the
    identical model."""
    port = _free_port()
    script = tmp_path / "ltr_worker.py"
    script.write_text(LTR_WORKER % {"repo": REPO})
    outs = [str(tmp_path / f"ltr_rank{r}.json") for r in range(2)]
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(script), str(r), str(port), outs[r]],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    for p in procs:
        try:
            _, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("lambdarank multihost worker timed out")
        assert p.returncode == 0, err.decode()[-2000:]
    r0 = json.load(open(outs[0]))
    r1 = json.load(open(outs[1]))
    assert r0["pred"] == r1["pred"]
    assert np.std(r0["pred"]) > 0.05   # learned a nontrivial ranking


GOSS_WORKER = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, %(repo)r)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:   # jax 0.4.x: the XLA_FLAGS above covers it
    pass
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except AttributeError:
    pass

rank = int(sys.argv[1])
port = sys.argv[2]
out = sys.argv[3]
os.environ["JAX_PROCESS_ID"] = str(rank)

import lightgbm_tpu as lgb

rng = np.random.default_rng(31)
n, nf = 2400, 6
X = rng.normal(size=(n, nf))
y = (X[:, 1] + 0.5 * X[:, 4] + rng.normal(size=n) * 0.3 > 0).astype(float)

params = {"objective": "binary", "boosting": "goss", "num_leaves": 15,
          "verbosity": -1, "num_machines": 2, "learning_rate": 0.2,
          "machines": "127.0.0.1:%%s,127.0.0.1:0" %% port,
          "top_rate": 0.2, "other_rate": 0.1,
          "min_data_in_leaf": 5, "tree_learner": "data"}
bst = lgb.train(params, lgb.Dataset(X, y), num_boost_round=12,
                verbose_eval=False)
pred = bst.predict(X[:400])
acc = float(((pred > 0.5) == y[:400]).mean())
with open(out, "w") as fh:
    json.dump({"rank": rank, "acc": acc,
               "pred": [round(float(p), 8) for p in pred[:200]]}, fh)
"""


@pytest.mark.slow
def test_python_api_distributed_goss(tmp_path):
    """boosting=goss over num_machines=2: the GLOBAL |g*h| threshold comes
    from the radix select with psum'd counts, warmup keeps all rows, and
    every rank materializes the identical model."""
    port = _free_port()
    script = tmp_path / "goss_worker.py"
    script.write_text(GOSS_WORKER % {"repo": REPO})
    outs = [str(tmp_path / f"goss_rank{r}.json") for r in range(2)]
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(script), str(r), str(port), outs[r]],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    for p in procs:
        try:
            _, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("goss multihost worker timed out")
        assert p.returncode == 0, err.decode()[-2000:]
    r0 = json.load(open(outs[0]))
    r1 = json.load(open(outs[1]))
    assert r0["pred"] == r1["pred"]
    assert r0["acc"] > 0.85, r0["acc"]


MV_WORKER = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, %(repo)r)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:   # jax 0.4.x: the XLA_FLAGS above covers it
    pass
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except AttributeError:
    pass

rank = int(sys.argv[1])
port = sys.argv[2]
out = sys.argv[3]
os.environ["JAX_PROCESS_ID"] = str(rank)

import lightgbm_tpu as lgb

rng = np.random.default_rng(41)
n, nf = 2400, 40
X = np.zeros((n, nf))
hit = rng.random((n, nf)) < 0.15
X[hit] = rng.normal(loc=1.0, size=int(hit.sum()))
beta = rng.normal(size=nf)
y = ((X @ beta) > 0).astype(float)

params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "num_machines": 2, "tpu_multival": "force",
          "machines": "127.0.0.1:%%s,127.0.0.1:0" %% port,
          "min_data_in_leaf": 5, "tree_learner": "data"}
bst = lgb.train(params, lgb.Dataset(X, y), num_boost_round=8,
                verbose_eval=False)
pred = bst.predict(X[:300])
acc = float(((pred > 0.5) == y[:300]).mean())
with open(out, "w") as fh:
    json.dump({"rank": rank, "acc": acc,
               "pred": [round(float(p), 8) for p in pred[:150]]}, fh)
"""


@pytest.mark.slow
def test_python_api_distributed_multival(tmp_path):
    """The multi-value (ELL) layout over num_machines=2: the row-sparse
    arrays shard with the rows across processes and the scatter
    histograms psum; both ranks materialize the identical model."""
    port = _free_port()
    script = tmp_path / "mv_worker.py"
    script.write_text(MV_WORKER % {"repo": REPO})
    outs = [str(tmp_path / f"mv_rank{r}.json") for r in range(2)]
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(script), str(r), str(port), outs[r]],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    for p in procs:
        try:
            _, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multival multihost worker timed out")
        assert p.returncode == 0, err.decode()[-2000:]
    r0 = json.load(open(outs[0]))
    r1 = json.load(open(outs[1]))
    assert r0["pred"] == r1["pred"]
    assert r0["acc"] > 0.8, r0["acc"]


QUANT_WORKER = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, %(repo)r)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:   # jax 0.4.x: the XLA_FLAGS above covers it
    pass
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except AttributeError:
    pass
from lightgbm_tpu.config import Config
from lightgbm_tpu.parallel.fingerprint import DivergenceError
from lightgbm_tpu.parallel.multihost import shard_rows, train_multihost
from lightgbm_tpu.resilience import faults

rank = int(sys.argv[1])
port = sys.argv[2]
out = sys.argv[3]

rng = np.random.default_rng(9)
n, nf = 3000, 8
X = rng.normal(size=(n, nf))
y = (X[:, 0] - 0.7 * X[:, 3] > 0).astype(float)
idx = shard_rows(n, rank, 2, False)

base = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
        "num_machines": 2,
        "machines": "127.0.0.1:%%s,127.0.0.1:0" %% port,
        "min_data_in_leaf": 5, "tree_learner": "data",
        "tpu_hist_quant": "int16", "tpu_divergence_probe": "on"}

# phase 1: quantized training must be bit-identical on every rank —
# the PR 14 divergence probe (model CRC + hist CRC per iteration over
# the metrics-values collective) must NOT fire
cfg = Config(dict(base))
faults.configure_from_config(cfg)
trees, mappers, ds, score = train_multihost(
    cfg, X[idx], y[idx], num_rounds=8, process_id=rank)
digest = [[int(t.num_leaves),
           [int(f) for f in t.split_feature[:t.num_leaves - 1]],
           [round(float(v), 9) for v in t.leaf_value[:t.num_leaves]]]
          for t in trees]

# phase 2: a genuinely corrupted quantized payload must still be CAUGHT
# — the corrupt_hist chaos verb perturbs rank 1's hist fingerprint at
# round 2, and the probe must raise on BOTH ranks naming hist
probe_fired = False
named_hist = False
cfg2 = Config(dict(base,
                   tpu_fault_plan="corrupt_hist@round=2;rank=1"))
faults.configure_from_config(cfg2)
try:
    train_multihost(cfg2, X[idx], y[idx], num_rounds=6, process_id=rank)
except DivergenceError as e:
    probe_fired = True
    named_hist = "hist" in str(e)

with open(out, "w") as fh:
    json.dump({"rank": rank, "digest": digest,
               "probe_fired": probe_fired,
               "named_hist": named_hist}, fh)
"""


@pytest.mark.slow
def test_two_process_quantized_bitexact_and_probe(tmp_path):
    """tpu_hist_quant=int16 over two real processes: the rank-uniform
    seeded stochastic rounding reconstructs the identical global
    histograms on every rank, so training is BIT-IDENTICAL and the
    divergence probe stays quiet — while a corrupt_hist chaos seed on
    the same quantized path still trips the probe on both ranks
    (quantization must not launder genuine corruption)."""
    port = _free_port()
    script = tmp_path / "quant_worker.py"
    script.write_text(QUANT_WORKER % {"repo": REPO})
    outs = [str(tmp_path / f"q_rank{r}.json") for r in range(2)]
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(script), str(r), str(port), outs[r]],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    for p in procs:
        try:
            _, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("quantized multihost worker timed out")
        assert p.returncode == 0, err.decode()[-2000:]
    r0 = json.load(open(outs[0]))
    r1 = json.load(open(outs[1]))
    assert r0["digest"] == r1["digest"], \
        "int16-quantized training diverged across ranks"
    assert r0["digest"][0][1][0] in (0, 3)      # learned the signal
    for r in (r0, r1):
        assert r["probe_fired"], "corrupt_hist probe did not fire"
        assert r["named_hist"], "probe must blame the hist component"
