"""Pallas histogram kernel vs XLA reference equivalence.

The analog of the reference's opt-in GPU_DEBUG_COMPARE CPU-vs-GPU histogram
diff (src/treelearner/gpu_tree_learner.cpp:993-1030): the Pallas kernel runs
in interpreter mode on CPU and must match the plain einsum bit-for-bit in
its f32 totals. The kernel's bf16 hi/lo gradient split carries a ~1e-7
relative residual-rounding error per element (the hi half is exact, the lo
half is itself bf16-rounded), so tolerances are f32-grade, not bitwise.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.ops.pallas_histogram import (HAS_PALLAS, hist_window,
                                               hist_window_xla)


@pytest.mark.skipif(not HAS_PALLAS, reason="pallas unavailable")
@pytest.mark.parametrize("C,G,W", [(512, 4, 64), (1024, 7, 256), (256, 1, 128)])
def test_pallas_hist_matches_xla(C, G, W):
    rng = np.random.default_rng(0)
    bins = rng.integers(0, W, size=(C, G)).astype(np.int32)
    grad = rng.normal(size=C).astype(np.float32)
    hess = rng.random(C).astype(np.float32)
    # mask a tail like the growers do
    grad[C // 2:] = 0.0
    hess[C // 2:] = 0.0

    ref = np.asarray(hist_window_xla(jnp.asarray(bins), jnp.asarray(grad),
                                     jnp.asarray(hess), W))
    out = np.asarray(hist_window(jnp.asarray(bins.T), jnp.asarray(grad),
                                 jnp.asarray(hess), W, interpret=True))
    assert out.shape == (G, W, 2)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


@pytest.mark.skipif(not HAS_PALLAS, reason="pallas unavailable")
def test_pallas_hist_totals_exact():
    """Per-group totals must equal the f32 sums exactly (bf16 hi/lo split)."""
    rng = np.random.default_rng(1)
    C, G, W = 2048, 3, 256
    bins = rng.integers(0, W, size=(C, G)).astype(np.int32)
    grad = (rng.normal(size=C) * 3).astype(np.float32)
    hess = rng.random(C).astype(np.float32)
    out = np.asarray(hist_window(jnp.asarray(bins.T), jnp.asarray(grad),
                                 jnp.asarray(hess), W, interpret=True))
    np.testing.assert_allclose(out[..., 0].sum(axis=1),
                               np.repeat(np.float64(grad.astype(np.float64).sum()), G),
                               rtol=1e-5)
    np.testing.assert_allclose(out[..., 1].sum(axis=1),
                               np.repeat(np.float64(hess.astype(np.float64).sum()), G),
                               rtol=1e-5)
