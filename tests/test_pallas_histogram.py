"""Pallas histogram kernel vs XLA reference equivalence.

The analog of the reference's opt-in GPU_DEBUG_COMPARE CPU-vs-GPU histogram
diff (src/treelearner/gpu_tree_learner.cpp:993-1030): the Pallas kernel runs
in interpreter mode on CPU and must match the plain einsum bit-for-bit in
its f32 totals. The kernel's bf16 hi/lo gradient split carries a ~1e-7
relative residual-rounding error per element (the hi half is exact, the lo
half is itself bf16-rounded), so tolerances are f32-grade, not bitwise.

Kernel invocations run under the strict-numerics harness
(analysis.strict_numerics: strict dtype promotion + debug-nans), so a
silent f64 leak into the f32 kernel math fails here even when the
numeric outputs still match.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.analysis import strict_numerics
from lightgbm_tpu.ops.pallas_histogram import (HAS_PALLAS, hist_window,
                                               hist_window_xla)


def _hist_strict(bins_t, grad, hess, w):
    with strict_numerics():
        out = hist_window(jnp.asarray(bins_t), jnp.asarray(grad),
                          jnp.asarray(hess), w, interpret=True)
        out.block_until_ready()
    return np.asarray(out)


@pytest.mark.skipif(not HAS_PALLAS, reason="pallas unavailable")
@pytest.mark.parametrize("C,G,W", [(512, 4, 64), (1024, 7, 256), (256, 1, 128)])
def test_pallas_hist_matches_xla(C, G, W):
    rng = np.random.default_rng(0)
    bins = rng.integers(0, W, size=(C, G)).astype(np.int32)
    grad = rng.normal(size=C).astype(np.float32)
    hess = rng.random(C).astype(np.float32)
    # mask a tail like the growers do
    grad[C // 2:] = 0.0
    hess[C // 2:] = 0.0

    ref = np.asarray(hist_window_xla(jnp.asarray(bins), jnp.asarray(grad),
                                     jnp.asarray(hess), W))
    out = _hist_strict(bins.T, grad, hess, W)
    assert out.shape == (G, W, 2)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def _scatter_ref(bins, grad, hess, w):
    """CPU scatter-add oracle (the reference ConstructHistogram inner
    loop, src/io/dense_bin.hpp:74): exact f64 bincount per group."""
    C, G = bins.shape
    out = np.zeros((G, w, 2), np.float64)
    for g in range(G):
        out[g, :, 0] = np.bincount(bins[:, g], weights=grad.astype(np.float64),
                                   minlength=w)[:w]
        out[g, :, 1] = np.bincount(bins[:, g], weights=hess.astype(np.float64),
                                   minlength=w)[:w]
    return out


@pytest.mark.skipif(not HAS_PALLAS, reason="pallas unavailable")
@pytest.mark.parametrize("C,G,W", [
    (768, 3, 256),    # byte groups -> radix-split kernel
    (768, 18, 256),   # the Expo geometry (few wide groups, radix)
    (768, 5, 16),     # nibble-width groups -> direct one-hot kernel
    (512, 2, 64),     # heuristic boundary: one-hot side
    (512, 2, 65),     # heuristic boundary: radix side
])
def test_kernel_variants_match_scatter_add(C, G, W):
    """Both kernel variants (radix-split for few wide groups, direct
    one-hot for narrow groups — ops/pallas_histogram._select_impl) must
    reproduce the CPU scatter-add path in interpreter mode, for nibble-
    width and byte-width storage alike."""
    from lightgbm_tpu.ops.pallas_histogram import _select_impl
    rng = np.random.default_rng(3 + W + G)
    bins = rng.integers(0, W, size=(C, G)).astype(np.int32)
    grad = rng.normal(size=C).astype(np.float32)
    hess = rng.random(C).astype(np.float32)
    ref = _scatter_ref(bins, grad, hess, W)
    out = _hist_strict(bins.T, grad, hess, W)
    assert out.shape == (G, W, 2)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)
    # pin the heuristic: wide groups radix, narrow groups one-hot
    use_radix = _select_impl(W, G, C)[0]
    assert use_radix == (W > 64)


def test_stripe_retune_few_groups():
    """The radix stripe length grows in the few-group regime and the
    one-hot kernel keeps its VMEM-bounded stripes."""
    from lightgbm_tpu.ops.pallas_histogram import _select_impl
    assert _select_impl(256, 4, 1 << 20)[2] == 32768     # Expo-ish: long
    assert _select_impl(256, 18, 1 << 20)[2] == 16384
    assert _select_impl(256, 64, 1 << 20)[2] == 8192     # many groups
    assert _select_impl(16, 40, 1 << 20)[2] == 16384     # narrow one-hot
    assert _select_impl(300, 2, 1 << 20)[2] == 8192      # uint16-wide
    assert _select_impl(256, 4, 4096)[2] == 4096         # capped by C


@pytest.mark.skipif(not HAS_PALLAS, reason="pallas unavailable")
def test_pallas_hist_totals_exact():
    """Per-group totals must equal the f32 sums exactly (bf16 hi/lo split)."""
    rng = np.random.default_rng(1)
    C, G, W = 2048, 3, 256
    bins = rng.integers(0, W, size=(C, G)).astype(np.int32)
    grad = (rng.normal(size=C) * 3).astype(np.float32)
    hess = rng.random(C).astype(np.float32)
    out = _hist_strict(bins.T, grad, hess, W)
    np.testing.assert_allclose(out[..., 0].sum(axis=1),
                               np.repeat(np.float64(grad.astype(np.float64).sum()), G),
                               rtol=1e-5)
    np.testing.assert_allclose(out[..., 1].sum(axis=1),
                               np.repeat(np.float64(hess.astype(np.float64).sum()), G),
                               rtol=1e-5)
