"""Perf-regression sentinel + roofline attribution (PR 11).

Covers the ISSUE-11 acceptance pins:

* synthetic round series: a regression beyond the band FAILS, an
  improvement passes, within-band noise passes, never-recorded
  trajectory keys are named loudly;
* round schema validation: malformed / meta-less rounds raise a clear
  RoundError instead of a KeyError mid-series;
* the meta block round-trips through bench.build_meta / BENCH_REPEATS
  median-of-k spread math;
* roofline fractions pinned against hand-computed values for two bench
  shapes + the bound taxonomy (hbm/compute/host/comms);
* the --perf CLI gates on a regressed synthetic series and runs green
  on the repo's real r01..r06 series (tier-1 smoke).
"""
import json
import math
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from lightgbm_tpu.analysis import perf_gate
from lightgbm_tpu.analysis.perf_gate import (RoundError, Verdict,  # noqa: F401
                                             evaluate, load_round,
                                             validate_round)
from lightgbm_tpu.telemetry import perfmodel
from lightgbm_tpu.telemetry.devices import get_profile

BAND = 0.15


def _round(index, parsed, meta=None):
    return validate_round({"parsed": parsed, "meta": meta},
                          "BENCH_r%02d.json" % index, index)


def _meta(device_kind="tpu-test", spread=None, knobs=None):
    return {"schema": 1, "device": {"kind": device_kind},
            "jax": "0.0", "knobs": knobs or {},
            "spread": spread or {}}


FULL = {"value": 10.0, "ranking_value": 5.0, "expo_value": 3.0,
        "expo_level_value": 4.0}


# ---------------------------------------------------------------------------
# trajectory verdicts on synthetic series
# ---------------------------------------------------------------------------

def test_regression_beyond_band_fails():
    rounds = [_round(1, FULL),
              _round(2, dict(FULL, value=7.0))]   # -30% >> 15% band
    rep = evaluate(rounds, BAND)
    assert [v.key for v in rep.regressions] == ["value"]
    results = {r.name: r for r in perf_gate.run(artifact=rep)}
    assert not results["perf_trajectory"].ok
    assert "value" in results["perf_trajectory"].detail


def test_improvement_and_within_band_pass():
    rounds = [_round(1, FULL),
              _round(2, dict(FULL, value=20.0,          # improved
                             ranking_value=4.8))]        # -4% within band
    rep = evaluate(rounds, BAND)
    assert not rep.regressions
    assert [v.key for v in rep.improvements] == ["value"]
    within = {v.key: v.status for v in rep.verdicts}
    assert within["ranking_value"] == "ok"
    results = {r.name: r for r in perf_gate.run(artifact=rep)}
    assert results["perf_trajectory"].ok


def test_missing_trajectory_key_named_loudly():
    parsed = {"value": 10.0, "ranking_value": 5.0, "expo_value": 3.0}
    rep = evaluate([_round(1, parsed), _round(2, parsed)], BAND)
    assert rep.missing_keys == ["expo_level_value"]
    results = {r.name: r for r in perf_gate.run(artifact=rep)}
    assert not results["perf_trajectory"].ok
    assert "expo_level_value" in results["perf_trajectory"].detail


def test_lower_better_keys_gate_in_the_right_direction():
    base = dict(FULL, predict_p99=0.010)
    rounds = [_round(1, base),
              _round(2, dict(base, predict_p99=0.020))]  # p99 doubled
    rep = evaluate(rounds, BAND)
    assert [v.key for v in rep.regressions] == ["predict_p99"]
    # and a p99 DROP is an improvement, not a regression
    rep2 = evaluate([_round(1, base),
                     _round(2, dict(base, predict_p99=0.005))], BAND)
    assert not rep2.regressions
    assert "predict_p99" in [v.key for v in rep2.improvements]


def test_device_change_opens_new_lineage_instead_of_regressing():
    # a CPU round after TPU rounds: NOT comparable — no regression even
    # though every number is 100x worse
    rounds = [_round(1, FULL),
              _round(2, {k: v / 100 for k, v in FULL.items()},
                     meta=_meta(device_kind="cpu"))]
    rep = evaluate(rounds, BAND)
    assert not rep.regressions
    assert len(rep.lineages) == 2
    statuses = {(v.key, v.round): v.status for v in rep.verdicts}
    assert statuses[("value", 2)] == "new"


def test_recorded_spread_widens_the_noise_band():
    # a 25% drop REGRESSES on the default band but passes when the
    # rounds recorded a 30% median-of-k spread for that key
    prev = _round(6, FULL, meta=_meta())
    noisy = _round(7, dict(FULL, value=7.5),
                   meta=_meta(spread={"value": 0.30}))
    rep = evaluate([prev, noisy], BAND)
    assert not rep.regressions
    tight = _round(7, dict(FULL, value=7.5), meta=_meta())
    rep2 = evaluate([prev, tight], BAND)
    assert [v.key for v in rep2.regressions] == ["value"]


def test_key_vanishing_from_latest_round_gates():
    """bench.py catches per-phase crashes and keeps going — a headline
    key the lineage used to record but the latest round lacks must FAIL
    the gate, not pass silently."""
    rounds = [_round(1, FULL),
              _round(2, {k: v for k, v in FULL.items()
                         if k != "expo_value"})]
    rep = evaluate(rounds, BAND)
    missing = [v for v in rep.verdicts if v.status == "missing"]
    assert [v.key for v in missing] == ["expo_value"]
    results = {r.name: r for r in perf_gate.run(artifact=rep)}
    assert not results["perf_trajectory"].ok
    assert "vanished" in results["perf_trajectory"].detail


def test_vanished_key_keeps_gating_on_later_rounds():
    """The predecessor for a key is the last round that CARRIED it —
    recording another crashed round must not launder the loss."""
    rounds = [_round(1, FULL),
              _round(2, {k: v for k, v in FULL.items()
                         if k != "expo_value"}),
              _round(3, {k: v for k, v in FULL.items()
                         if k != "expo_value"})]
    rep = evaluate(rounds, BAND)
    missing = [v for v in rep.verdicts if v.status == "missing"]
    assert [(v.key, v.round, v.prev_round) for v in missing] == \
        [("expo_value", 3, 1)]
    results = {r.name: r for r in perf_gate.run(artifact=rep)}
    assert not results["perf_trajectory"].ok
    # and a key SKIPPING a round compares against its real last carrier
    rep2 = evaluate([_round(1, FULL),
                     _round(2, {k: v for k, v in FULL.items()
                                if k != "value"}),
                     _round(3, dict(FULL, value=5.0))], BAND)
    reg = [v for v in rep2.regressions if v.key == "value"]
    assert reg and reg[0].prev_round == 1


def test_median_merge_nested_predict_layout():
    import bench
    runs = [{"higgs": {"value": 1.0}, "poisson": {"p99": 0.010}},
            {"higgs": {"value": 1.2}, "poisson": {"p99": 0.030}},
            {"higgs": {"value": 1.1}, "poisson": {"p99": 0.020}}]
    merged, spread = bench._median_merge_nested(
        runs, ("higgs", "expo", "poisson"))
    assert merged["higgs"]["value"] == pytest.approx(1.1)
    assert merged["poisson"]["p99"] == pytest.approx(0.020)
    assert spread["poisson.p99"] == pytest.approx(0.020 / 0.020)
    assert "expo" not in spread  # sub-dict absent from every run


def test_find_phase_snapshot_numeric_round_order(tmp_path):
    from lightgbm_tpu.telemetry import perfmodel
    assert perfmodel.find_phase_snapshot(str(tmp_path)) is None
    for n in (9, 10, 100, 99):
        (tmp_path / ("BENCH_r%02d_phases.json" % n)).write_text("{}")
    got = perfmodel.find_phase_snapshot(str(tmp_path))
    assert got.endswith("BENCH_r100_phases.json")
    (tmp_path / "only" ).mkdir()
    (tmp_path / "only" / "BENCH_phases.json").write_text("{}")
    assert perfmodel.find_phase_snapshot(
        str(tmp_path / "only")).endswith("BENCH_phases.json")


def test_perf_card_rejects_non_object_snapshot(tmp_path, capsys):
    from lightgbm_tpu.profile import main
    p = tmp_path / "snap.json"
    p.write_text("[]")   # valid JSON, wrong shape
    assert main(["--perf-card", "higgs", str(p)]) == 2
    assert "not a JSON object" in capsys.readouterr().err


def test_measurement_knobs_do_not_sever_the_lineage():
    """BENCH_REPEATS / BENCH_TELEMETRY / BENCH_SKIP_* / *_OUT change how
    a round is MEASURED, not what it measures — flipping them must keep
    the regression comparison alive."""
    meta_a = _meta(knobs={"BENCH_ROWS": "1000"})
    meta_b = _meta(knobs={"BENCH_ROWS": "1000", "BENCH_REPEATS": "3",
                          "BENCH_TELEMETRY": "0", "BENCH_SKIP_EXPO": "1",
                          "BENCH_PHASES_OUT": "x.json"})
    rounds = [_round(6, FULL, meta=meta_a),
              _round(7, dict(FULL, value=5.0), meta=meta_b)]
    rep = evaluate(rounds, BAND)
    assert len(rep.lineages) == 1
    assert [v.key for v in rep.regressions] == ["value"]
    # a WORKLOAD knob change does sever it
    meta_c = _meta(knobs={"BENCH_ROWS": "9999"})
    rep2 = evaluate([_round(6, FULL, meta=meta_a),
                     _round(7, dict(FULL, value=5.0), meta=meta_c)],
                    BAND)
    assert len(rep2.lineages) == 2 and not rep2.regressions


def test_check_fixture_positive_and_negative():
    bad = [{"index": 1, "parsed": FULL},
           {"index": 2, "parsed": dict(FULL, value=5.0)}]
    assert perf_gate.check_fixture(bad)
    good = [{"index": 1, "parsed": FULL},
            {"index": 2, "parsed": dict(FULL, value=11.0)}]
    assert not perf_gate.check_fixture(good)


# ---------------------------------------------------------------------------
# round schema validation
# ---------------------------------------------------------------------------

def test_malformed_round_raises_clear_error():
    with pytest.raises(RoundError, match="parsed"):
        validate_round({"tail": "..."}, "BENCH_r03.json", 3)
    with pytest.raises(RoundError, match="object"):
        validate_round([1, 2], "BENCH_r03.json", 3)


def test_metaless_round_grandfathered_only_before_r06():
    # r01..r05 predate the meta block: accepted as legacy
    r = validate_round({"parsed": {"value": 1.0}}, "BENCH_r05.json", 5)
    assert r.legacy and r.fingerprint() == "legacy"
    with pytest.raises(RoundError, match="meta"):
        validate_round({"parsed": {"value": 1.0}}, "BENCH_r07.json", 7)


def test_meta_missing_required_fields_rejected():
    with pytest.raises(RoundError, match="schema"):
        validate_round({"parsed": {"value": 1.0},
                        "meta": {"device": {}, "jax": "0.0"}},
                       "BENCH_r07.json", 7)
    with pytest.raises(RoundError, match="object"):
        validate_round({"parsed": {"value": 1.0}, "meta": "v1"},
                       "BENCH_r07.json", 7)


def test_load_round_bad_json_and_bad_name(tmp_path):
    p = tmp_path / "BENCH_r09.json"
    p.write_text("{not json")
    with pytest.raises(RoundError, match="unreadable"):
        load_round(str(p))
    with pytest.raises(RoundError, match="not a BENCH"):
        load_round(str(tmp_path / "OTHER.json"))


def test_meta_rides_inside_parsed_too():
    """bench.py stamps meta into its printed metric line; the driver
    archives that line as `parsed` — the validator finds it there."""
    r = validate_round({"parsed": {"value": 1.0, "meta": _meta()}},
                       "BENCH_r07.json", 7)
    assert not r.legacy and r.meta["schema"] == 1


# ---------------------------------------------------------------------------
# bench meta block + BENCH_REPEATS median-of-k spread
# ---------------------------------------------------------------------------

def test_median_merge_and_spread():
    import bench
    runs = [{"value": 1.0, "train_s": 10.0, "rows": 500},
            {"value": 1.2, "train_s": 8.0, "rows": 500},
            {"value": 1.1, "train_s": 9.0, "rows": 500}]
    merged, spread = bench._median_merge(runs)
    assert merged["value"] == pytest.approx(1.1)
    assert merged["train_s"] == pytest.approx(9.0)
    assert merged["rows"] == 500 and isinstance(merged["rows"], int)
    assert spread["value"] == pytest.approx(0.2 / 1.1)
    assert spread["rows"] == 0.0


def test_repeat_phase_single_run_has_no_spread():
    import bench
    out, spread = bench._repeat_phase(lambda: {"value": 2.0}, 1)
    assert out == {"value": 2.0} and spread == {}


def test_build_meta_roundtrips_through_validator(monkeypatch):
    import bench
    monkeypatch.setenv("BENCH_ROWS", "1234")
    monkeypatch.setenv("BENCH_REPEATS", "3")
    meta = bench.build_meta(repeats=3, spread={"value": 0.0512345})
    assert meta["schema"] == bench.BENCH_SCHEMA_VERSION
    assert meta["knobs"]["BENCH_ROWS"] == "1234"
    assert meta["repeats"] == 3
    assert meta["spread"]["value"] == pytest.approx(0.0512, abs=1e-4)
    assert meta["device"]["profile"]["name"]
    r = validate_round({"parsed": {"value": 1.0}, "meta": meta},
                       "BENCH_r07.json", 7)
    assert not r.legacy
    # the lineage fingerprint keys off device + workload knobs
    assert "BENCH_ROWS=1234" in r.fingerprint()


def test_bench_params_knob_parsing(monkeypatch):
    import bench
    monkeypatch.setenv("BENCH_PARAMS",
                       "tpu_persist_scan=force, num_leaves=63")
    assert bench._extra_params() == {"tpu_persist_scan": "force",
                                     "num_leaves": "63"}
    p = bench._phase_params({"num_leaves": 255, "objective": "binary"})
    assert p["num_leaves"] == "63" and p["objective"] == "binary"
    monkeypatch.delenv("BENCH_PARAMS")
    assert bench._extra_params() == {}


# ---------------------------------------------------------------------------
# roofline: hand-computed pins for two bench shapes + bound taxonomy
# ---------------------------------------------------------------------------

def _snap(wall_ops, wall_other, program_total, program_count=10,
          comms_total=0.0, work=None):
    histos = {}
    if program_count:
        histos[perfmodel.PROGRAM_WALL_HISTO] = {
            "count": program_count, "total": program_total}
    if comms_total:
        histos["collective::allreduce::latency"] = {
            "count": 4, "total": comms_total}
    return {"categories": {"ops": wall_ops, "boosting": wall_other},
            "histograms": histos, "work": work or {}}


def test_work_model_hand_computed_higgs():
    # rows=1000 iters=10 leaves=255 -> depth 8, nodes 509,
    # rows_scanned = 1000 * (1 + 3.5) = 4500
    m = perfmodel.work_model(rows=1000, groups=28, features=28,
                             iters=10, num_leaves=255)
    assert m["depth"] == 8 and m["nodes"] == 509
    assert m["rows_scanned"] == pytest.approx(4500.0)
    hist_bytes = 4500 * (28 + 8)                      # 162_000
    plane_bytes = 509 * 28 * 256 * 2 * 4 * 2          # 58_363_904
    assert m["bytes"] == pytest.approx(10 * (hist_bytes + plane_bytes))
    flops = 4500 * 28 * 2 + 509 * 28 * 256 * 8        # 29_436_904
    assert m["flops"] == pytest.approx(10 * flops)


def test_report_card_fraction_pinned_higgs_v5e():
    prof = get_profile("v5e")
    work = {"rows": 10_500_000, "iters": 500, "num_leaves": 255}
    snap = _snap(wall_ops=10.0, wall_other=2.0, program_total=10.0,
                 work=work)
    card = perfmodel.report_card(snap, "higgs", profile=prof)
    m = perfmodel.work_model(10_500_000, 28, 28, 500, 255)
    t_hbm = m["bytes"] / 819e9
    t_comp = m["flops"] / (197e12 * perfmodel.F32_DERATE)
    assert t_hbm > t_comp                  # HIGGS hist build streams HBM
    assert card.bound == "hbm"
    assert card.achieved_frac == pytest.approx(t_hbm / 10.0, rel=1e-6)
    assert card.t_hbm == pytest.approx(t_hbm, rel=1e-6)


def test_report_card_fraction_pinned_expo_v5e():
    # expo bundles 648 features into 18 byte groups: the plane traffic
    # collapses but the split scan still walks all 648 features
    prof = get_profile("v5e")
    work = {"rows": 2_000_000, "iters": 96, "num_leaves": 255}
    snap = _snap(wall_ops=8.0, wall_other=1.0, program_total=8.0,
                 work=work)
    card = perfmodel.report_card(snap, "expo", profile=prof)
    m = perfmodel.work_model(2_000_000, 18, 648, 96, 255)
    t_hbm = m["bytes"] / 819e9
    t_comp = m["flops"] / (197e12 * perfmodel.F32_DERATE)
    expect = max(t_hbm, t_comp)
    assert card.achieved_frac == pytest.approx(expect / 8.0, rel=1e-6)
    assert card.bound == ("hbm" if t_hbm >= t_comp else "compute")
    assert card.rows == 2_000_000 and card.iters == 96


def test_bound_taxonomy_host_and_comms():
    work = {"rows": 20_000, "iters": 8, "num_leaves": 63}
    # programs took 1% of the wall: the python driver binds, not the chip
    host = perfmodel.report_card(
        _snap(wall_ops=0.1, wall_other=9.9, program_total=0.1,
              work=work), "higgs", profile=get_profile("v5e"))
    assert host.bound == "host"
    # DCN time over 40% of wall: comms-bound
    comms = perfmodel.report_card(
        _snap(wall_ops=4.0, wall_other=1.0, program_total=4.0,
              comms_total=4.0, work=work),
        "higgs", profile=get_profile("v5e"))
    assert comms.bound == "comms"


def test_cards_from_phases_covers_the_five_shapes():
    work = {"rows": 1000, "iters": 4, "num_leaves": 63}
    snaps = {k: _snap(1.0, 0.1, 1.0, work=work)
             for k in ("higgs", "ltr", "expo", "allstate", "yahoo_ltr")}
    cards = perfmodel.cards_from_phases(snaps,
                                        profile=get_profile("v5e"))
    assert sorted(c.shape for c in cards) == [
        "allstate", "expo", "higgs", "msltr", "yahoo"]
    for c in cards:
        assert c.bound in ("compute", "hbm", "comms", "host")
        assert c.achieved_frac >= 0.0
    text = perfmodel.render_cards(cards)
    assert "perf report card" in text and "bound" in text


def test_format_report_appends_perf_cards():
    from lightgbm_tpu.telemetry import export
    card = perfmodel.report_card(
        _snap(1.0, 0.1, 1.0, work={"rows": 1000, "iters": 4,
                                   "num_leaves": 63}),
        "higgs", profile=get_profile("v5e"))
    text = export.format_report(snap={}, perf_cards=[card])
    assert "perf report card" in text and "higgs" in text


# ---------------------------------------------------------------------------
# the real repo series + the CLI gate
# ---------------------------------------------------------------------------

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_repo_round_series_green():
    """The acceptance pin: the archived r01..r06 series passes the
    sentinel — r06 carries the meta block and the expo_level_* keys, so
    the stale-trajectory failure mode is CLOSED."""
    rounds, multichip, errors = perf_gate.discover_rounds(REPO_ROOT)
    assert not errors
    assert len(rounds) >= 6
    r06 = [r for r in rounds if r.index == 6]
    assert r06 and not r06[0].legacy, "r06 must be self-describing"
    assert "expo_level_value" in r06[0].parsed
    rep = evaluate(rounds, 0.15, multichip=multichip, errors=errors)
    results = {r.name: r for r in perf_gate.run(artifact=rep)}
    assert results["perf_rounds"].ok
    assert results["perf_trajectory"].ok, \
        results["perf_trajectory"].detail
    assert results["perf_multichip"].ok


def test_perf_cli_green_and_tables(capsys):
    from lightgbm_tpu.analysis.__main__ import main
    rc = main(["lightgbm_tpu/analysis/perf_gate.py", "--no-audit",
               "--perf", "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0, payload["audits"]
    names = {a["name"] for a in payload["audits"]}
    assert {"perf_rounds", "perf_trajectory"} <= names
    pt = payload["perf_tables"]
    assert pt["rounds"][0]["index"] == 1
    assert "value" in pt["trajectories"]
    assert not pt["missing_keys"]
    # the archived r06 phase snapshot feeds the roofline cards: all five
    # bench shapes get a bound + achieved fraction (acceptance pin)
    shapes = {c["shape"]: c for c in pt["roofline"]["cards"]}
    assert set(shapes) == {"higgs", "msltr", "expo", "allstate",
                           "yahoo"}
    for c in shapes.values():
        assert c["bound"] in ("compute", "hbm", "comms", "host")
        assert isinstance(c["achieved_frac"], float)


def test_perf_cli_fails_on_regressed_series(tmp_path, monkeypatch,
                                            capsys):
    """The demonstrable-failure pin: a synthetic regressed round flips
    the SAME CLI invocation to exit 1 (and advisory mode back to 0)."""
    for i, v in ((1, 10.0), (2, 4.0)):
        (tmp_path / ("BENCH_r%02d.json" % i)).write_text(json.dumps(
            {"parsed": dict(FULL, value=v)}))
    monkeypatch.setenv("LGBTPU_PERF_ROUNDS_DIR", str(tmp_path))
    from lightgbm_tpu.analysis.__main__ import main
    rc = main(["lightgbm_tpu/analysis/perf_gate.py", "--no-audit",
               "--perf", "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    traj = [a for a in payload["audits"]
            if a["name"] == "perf_trajectory"][0]
    assert not traj["ok"] and "value" in traj["detail"]
    # advisory mode reports the same verdict but never blocks
    rc = main(["lightgbm_tpu/analysis/perf_gate.py", "--no-audit",
               "--perf-advisory"])
    out = capsys.readouterr().out
    assert rc == 0 and "ADVISORY-FAIL" in out


def test_perf_cli_zero_rounds_reports_cleanly(tmp_path, monkeypatch,
                                              capsys):
    """A directory with ZERO BENCH_r* rounds is reported as "no rounds
    recorded" with a RoundError-style message — gate mode exits 1
    (judging nothing is a bench-refresh bug), advisory mode exits 0 —
    never a traceback, never a silent pass."""
    monkeypatch.setenv("LGBTPU_PERF_ROUNDS_DIR", str(tmp_path))
    from lightgbm_tpu.analysis.__main__ import main
    rc = main(["lightgbm_tpu/analysis/perf_gate.py", "--no-audit",
               "--perf", "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1 and payload["exit_code"] == 1
    rounds = [a for a in payload["audits"]
              if a["name"] == "perf_rounds"][0]
    assert not rounds["ok"] and "rounds recorded" in rounds["detail"]
    assert str(tmp_path) in rounds["detail"]
    traj = [a for a in payload["audits"]
            if a["name"] == "perf_trajectory"][0]
    assert traj["ok"] and traj["skipped"]
    # the pre-commit advisory mode reports the same state, exit 0
    rc = main(["lightgbm_tpu/analysis/perf_gate.py", "--no-audit",
               "--perf-advisory"])
    out = capsys.readouterr().out
    assert rc == 0 and "ADVISORY-FAIL" in out
    # evaluate()/run() on the empty series also stay exception-free
    rep = evaluate([], BAND)
    results = perf_gate.run(artifact=rep)
    assert any(not r.ok for r in results)
    # a multichip-only archive still gets its series judged: the
    # zero-BENCH-rounds failure must not swallow the multichip verdict
    rep_mc = evaluate([], BAND, multichip=[
        {"index": 1, "ok": True, "rc": 0, "n_devices": 8}])
    names = {r.name: r for r in perf_gate.run(artifact=rep_mc)}
    assert not names["perf_rounds"].ok
    assert "perf_multichip" in names and names["perf_multichip"].ok
    # ...and in the sibling state where every BENCH round failed to
    # PARSE, a failing multichip series must still be reported
    rep_err = evaluate([], BAND,
                       multichip=[{"index": 1, "ok": False, "rc": 1}],
                       errors=["BENCH_r01.json: unreadable round json"])
    names = {r.name: r for r in perf_gate.run(artifact=rep_err)}
    assert not names["perf_rounds"].ok
    assert not names["perf_multichip"].ok


def test_profile_perf_card_cli(tmp_path, capsys):
    """profile --perf-card SHAPE reads an archived snapshot — no bench
    re-run, no accelerator."""
    snap = {"higgs": _snap(2.0, 0.5, 2.0,
                           work={"rows": 50_000, "iters": 10,
                                 "num_leaves": 63})}
    p = tmp_path / "BENCH_phases.json"
    p.write_text(json.dumps(snap))
    from lightgbm_tpu.profile import main
    assert main(["--perf-card", "higgs", str(p), "--json"]) == 0
    card = json.loads(capsys.readouterr().out)
    assert card["shape"] == "higgs" and card["bound"] in (
        "compute", "hbm", "comms", "host")
    # directory form picks the snapshot up too
    assert main(["--perf-card", "higgs", str(tmp_path)]) == 0
    assert "perf report card" in capsys.readouterr().out
    # a missing shape is a clear error, not a traceback
    assert main(["--perf-card", "nope", str(p)]) == 2
