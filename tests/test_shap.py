"""SHAP contribution tests.

Two independent checks pin TreeSHAP correctness:
1. columns of pred_contrib sum to the raw prediction (the local-accuracy
   property, also the reference python package's usual assertion);
2. a brute-force Shapley computation on a tiny model — explicit enumeration
   over feature subsets with the tree-conditional expectation (EXPVALUE in
   Lundberg et al., Algorithm 1) — must match exactly.
The native C++ kernel and the pure-Python fallback are both exercised.
"""
import itertools
import math

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _make(n=600, f=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] + 0.1 * rng.normal(size=n))
    return X, y


def _expvalue(tree, x, subset):
    """Conditional expectation of the tree with only `subset` features known."""
    def rec(node):
        if node < 0:
            return float(tree.leaf_value[~node])
        f = int(tree.split_feature[node])
        left, right = int(tree.left_child[node]), int(tree.right_child[node])
        if f in subset:
            go_left = bool(tree._decision(
                np.array([x[f]]), np.array([node], dtype=np.int32))[0])
            return rec(left if go_left else right)
        def cover(c):
            return (float(tree.internal_count[c]) if c >= 0
                    else float(tree.leaf_count[~c]))
        cn = cover(node)
        return (cover(left) * rec(left) + cover(right) * rec(right)) / cn
    return rec(0)


def _brute_shap(tree, x, nf):
    phi = np.zeros(nf + 1)
    feats = list(range(nf))
    for i in feats:
        rest = [f for f in feats if f != i]
        for k in range(len(rest) + 1):
            for S in itertools.combinations(rest, k):
                wt = (math.factorial(len(S)) * math.factorial(nf - len(S) - 1)
                      / math.factorial(nf))
                phi[i] += wt * (_expvalue(tree, x, set(S) | {i})
                                - _expvalue(tree, x, set(S)))
    phi[-1] = _expvalue(tree, x, set())
    return phi


def test_contrib_sums_to_prediction():
    X, y = _make()
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(X, y), 25,
                    verbose_eval=False)
    contrib = bst.predict(X[:100], pred_contrib=True)
    assert contrib.shape == (100, X.shape[1] + 1)
    raw = bst.predict(X[:100], raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-6,
                               atol=1e-8)


def test_contrib_matches_bruteforce_shapley():
    X, y = _make(n=400, f=4, seed=3)
    bst = lgb.train({"objective": "regression", "num_leaves": 8,
                     "min_data_in_leaf": 10, "verbosity": -1},
                    lgb.Dataset(X, y), 1, verbose_eval=False)
    bst._booster._materialize_pending()
    tree = bst._booster.models[0]
    nf = X.shape[1]
    for r in range(5):
        got = np.zeros((1, nf + 1))
        tree.predict_contrib(X[r:r + 1], nf, got)
        want = _brute_shap(tree, X[r], nf)
        np.testing.assert_allclose(got[0], want, rtol=1e-9, atol=1e-10)


def test_contrib_python_fallback_matches_native(monkeypatch):
    from lightgbm_tpu import native
    X, y = _make(n=200, f=4, seed=5)
    bst = lgb.train({"objective": "regression", "num_leaves": 12,
                     "verbosity": -1}, lgb.Dataset(X, y), 3,
                    verbose_eval=False)
    a = bst.predict(X[:40], pred_contrib=True)
    monkeypatch.setattr(native, "load", lambda name: None)
    b = bst.predict(X[:40], pred_contrib=True)
    np.testing.assert_allclose(a, b, rtol=1e-10, atol=1e-12)


def test_contrib_multiclass_layout():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(500, 4))
    y = rng.integers(0, 3, size=500)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "verbosity": -1},
                    lgb.Dataset(X, y), 5, verbose_eval=False)
    c = bst.predict(X[:20], pred_contrib=True)
    assert c.shape == (20, 3 * (4 + 1))
    raw = bst.predict(X[:20], raw_score=True)
    got = c.reshape(20, 3, 5).sum(axis=2)
    np.testing.assert_allclose(got, raw, rtol=1e-6, atol=1e-8)


def test_pred_early_stop():
    """prediction_early_stop.cpp analog: high-margin rows skip later trees
    and predictions stay close (identical labels for confident rows)."""
    X, y = _make(n=2000, f=5, seed=9)
    labels = (y > np.median(y)).astype(float)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1}, lgb.Dataset(X, labels), 60,
                    verbose_eval=False)
    full = bst.predict(X)
    es = bst.predict(X, pred_early_stop=True, pred_early_stop_freq=5,
                     pred_early_stop_margin=4.0)
    # early-stopped rows keep the same decision
    assert (((full > 0.5) == (es > 0.5)).mean()) > 0.999
    # and a huge margin threshold means no early stop at all
    same = bst.predict(X, pred_early_stop=True, pred_early_stop_freq=5,
                       pred_early_stop_margin=1e30)
    np.testing.assert_allclose(same, full, rtol=1e-12)
