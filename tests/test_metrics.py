"""Metric unit tests with hand-computed golden values
(reference strategy: tests/python_package_test pins metric outputs)."""
import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.data.dataset import Metadata
from lightgbm_tpu.metrics import create_metric


def _eval(name, label, score, weight=None, group=None, params=None,
          objective=None):
    cfg = Config(params or {})
    m = create_metric(name, cfg)
    md = Metadata(len(label))
    md.set_label(np.asarray(label))
    if weight is not None:
        md.set_weight(np.asarray(weight))
    if group is not None:
        md.set_query(np.asarray(group))
    m.init(md, len(label))
    return m.eval(np.asarray(score, dtype=np.float64), objective)


def test_l2_and_rmse():
    y = [1.0, 2.0, 3.0]
    p = [1.5, 2.0, 2.0]
    assert _eval("l2", y, p)[0] == pytest.approx((0.25 + 0 + 1) / 3)
    assert _eval("rmse", y, p)[0] == pytest.approx(np.sqrt((0.25 + 0 + 1) / 3))


def test_l1_weighted():
    y = [1.0, 2.0]
    p = [2.0, 0.0]
    w = [3.0, 1.0]
    assert _eval("l1", y, p, weight=w)[0] == pytest.approx((3 * 1 + 1 * 2) / 4)


def test_binary_logloss():
    y = [0, 1]
    prob = [0.25, 0.75]
    expected = (-np.log(0.75) - np.log(0.75)) / 2
    assert _eval("binary_logloss", y, prob)[0] == pytest.approx(expected)


def test_binary_error():
    y = [0, 1, 1, 0]
    prob = [0.4, 0.6, 0.4, 0.6]
    assert _eval("binary_error", y, prob)[0] == pytest.approx(0.5)


def test_auc_perfect_and_random():
    y = [0, 0, 1, 1]
    assert _eval("auc", y, [0.1, 0.2, 0.8, 0.9])[0] == pytest.approx(1.0)
    assert _eval("auc", y, [0.9, 0.8, 0.2, 0.1])[0] == pytest.approx(0.0)
    # ties: all equal scores -> 0.5
    assert _eval("auc", y, [0.5] * 4)[0] == pytest.approx(0.5)


def test_auc_weighted():
    y = [0, 1]
    w = [2.0, 3.0]
    assert _eval("auc", y, [0.1, 0.9], weight=w)[0] == pytest.approx(1.0)


def test_ndcg():
    # one query, 3 docs, labels 2,1,0 ranked perfectly
    y = [2, 1, 0]
    score = [3.0, 2.0, 1.0]
    vals = _eval("ndcg", y, score, group=[3], params={"eval_at": [3]})
    assert vals[0] == pytest.approx(1.0)
    # worst order
    vals = _eval("ndcg", y, [1.0, 2.0, 3.0], group=[3],
                 params={"eval_at": [3]})
    gain = [3.0, 1.0, 0.0]   # 2^l - 1
    disc = 1.0 / np.log2(np.arange(3) + 2)
    dcg = gain[2] * disc[0] + gain[1] * disc[1] + gain[0] * disc[2]
    max_dcg = gain[0] * disc[0] + gain[1] * disc[1] + gain[2] * disc[2]
    assert vals[0] == pytest.approx(dcg / max_dcg)


def test_map():
    # one query: relevant docs at ranks 1 and 3
    y = [1, 0, 1, 0]
    score = [4.0, 3.0, 2.0, 1.0]
    vals = _eval("map", y, score, group=[4], params={"eval_at": [4]})
    expected = (1.0 / 1 + 2.0 / 3) / 2
    assert vals[0] == pytest.approx(expected)


def test_multi_logloss():
    y = [0, 1]
    # class-major scores [K*N]: probabilities passed directly (no objective)
    probs = np.array([[0.7, 0.2], [0.3, 0.8]])   # [K=2, N=2]
    vals = _eval("multi_logloss", y, probs.reshape(-1),
                 params={"num_class": 2})
    expected = (-np.log(0.7) - np.log(0.8)) / 2
    assert vals[0] == pytest.approx(expected)


def test_multi_error_topk():
    y = [0, 1]
    probs = np.array([[0.4, 0.2], [0.6, 0.8]])
    vals = _eval("multi_error", y, probs.reshape(-1),
                 params={"num_class": 2})
    assert vals[0] == pytest.approx(0.5)
    vals = _eval("multi_error", y, probs.reshape(-1),
                 params={"num_class": 2, "multi_error_top_k": 2})
    assert vals[0] == pytest.approx(0.0)


def test_auc_mu_binaryish():
    # 2-class auc_mu equals plain AUC on separable data
    y = [0, 0, 1, 1]
    probs = np.array([[0.9, 0.8, 0.2, 0.1], [0.1, 0.2, 0.8, 0.9]])
    vals = _eval("auc_mu", y, probs.reshape(-1), params={"num_class": 2})
    assert vals[0] == pytest.approx(1.0)


def test_poisson_metric():
    y = [1.0, 2.0]
    mu = [1.0, 2.0]
    expected = np.mean([1 - 1 * np.log(1), 2 - 2 * np.log(2)])
    assert _eval("poisson", y, mu)[0] == pytest.approx(expected)


def test_quantile_metric():
    y = [1.0, 1.0]
    p = [0.0, 2.0]
    # alpha=0.9: under-prediction penalized 0.9, over penalized 0.1
    vals = _eval("quantile", y, p, params={"alpha": 0.9})
    assert vals[0] == pytest.approx((0.9 * 1 + 0.1 * 1) / 2)


def test_xentropy_soft_labels():
    y = [0.3]
    p = [0.3]
    expected = -(0.3 * np.log(0.3) + 0.7 * np.log(0.7))
    assert _eval("cross_entropy", y, p)[0] == pytest.approx(expected)


def test_kldiv():
    y = [0.3]
    p = [0.3]
    # KL(y||p) = 0 when p == y
    assert _eval("kldiv", y, p)[0] == pytest.approx(0.0, abs=1e-12)
