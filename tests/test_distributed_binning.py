"""Distributed bin-mapper construction (the
DatasetLoader::ConstructBinMappersFromTextData analog,
src/io/dataset_loader.cpp:824-975): per-rank feature-slice FindBin +
allgather of serialized mappers. Simulated here with W in-process "ranks"
and a loopback allgather; asserts every rank reassembles the identical
global mapper list, shard layouts line up bin-for-bin, and a model trained
on the synced shards matches a reference construction."""
import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.data.dataset import BinnedDataset
from lightgbm_tpu.parallel.distributed import (_feature_slice,
                                               distributed_bin_mappers,
                                               parse_machine_list)


class _Collected(Exception):
    pass


def _simulate(world, X_shards, config, cat=()):
    """Run the per-rank halves with a loopback allgather: phase 1 captures
    each rank's serialized slice, phase 2 hands every rank the full set."""
    states_by_rank = [None] * world
    for r in range(world):
        def collect(payload, r=r):
            states_by_rank[r] = payload
            raise _Collected()
        try:
            distributed_bin_mappers(X_shards[r], X_shards[r].shape[0],
                                    config, categorical_features=cat,
                                    rank=r, world=world, allgather=collect)
        except _Collected:
            pass

    def full_allgather(payload):
        return states_by_rank
    return [distributed_bin_mappers(
        X_shards[r], X_shards[r].shape[0], config,
        categorical_features=cat, rank=r, world=world,
        allgather=full_allgather) for r in range(world)]


def test_feature_slices_cover_all():
    for world in (1, 2, 3, 4, 7):
        for F in (1, 5, 28, 100):
            seen = []
            for r in range(world):
                s, ln = _feature_slice(r, world, F)
                seen.extend(range(s, s + ln))
            assert seen == list(range(F))


def test_distributed_mappers_identical_across_ranks():
    rng = np.random.default_rng(0)
    world = 4
    X = rng.normal(size=(8000, 10))
    X[:, 7] = rng.integers(0, 6, 8000)
    shards = np.split(X, world)
    cfg = lgb.Config({"max_bin": 63})
    per_rank = _simulate(world, shards, cfg, cat=(7,))
    ref = per_rank[0]
    for r in range(1, world):
        for a, b in zip(ref, per_rank[r]):
            assert a.to_state() == b.to_state()
    # rank r's slice really came from rank r's local sample
    s, ln = _feature_slice(1, world, 10)
    from lightgbm_tpu.data.bin_mapper import BinMapper, BinType
    from lightgbm_tpu.data.bin_mapper import kZeroThreshold
    col = shards[1][:, s]
    nz = col[(np.abs(col) > kZeroThreshold) | np.isnan(col)]
    m = BinMapper()
    m.find_bin(nz, shards[1].shape[0], cfg.max_bin, cfg.min_data_in_bin,
               max(int(cfg.min_data_in_leaf * 1.0), 1), pre_filter=True,
               bin_type=BinType.NUMERICAL, use_missing=cfg.use_missing,
               zero_as_missing=cfg.zero_as_missing)
    assert m.to_state() == ref[s].to_state()


def test_shard_datasets_share_layout_and_train():
    rng = np.random.default_rng(1)
    world = 4
    n = 6000
    X = rng.normal(size=(n, 8))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    shards = np.split(X, world)
    yshards = np.split(y, world)
    cfg = lgb.Config({"max_bin": 63})
    mappers = _simulate(world, shards, cfg)[0]
    dsets = [BinnedDataset.from_matrix_with_mappers(
        shards[r], cfg, mappers, label=yshards[r]) for r in range(world)]
    a = dsets[0]
    for d in dsets[1:]:
        assert d.total_bins == a.total_bins
        assert d.groups == a.groups
        np.testing.assert_array_equal(d.bin_start, a.bin_start)
    # the reassembled global matrix must equal binning the full X with the
    # same mappers in one shot
    full = BinnedDataset.from_matrix_with_mappers(X, cfg, mappers, label=y)
    np.testing.assert_array_equal(
        np.concatenate([d.binned for d in dsets]), full.binned)
    # and the full dataset trains fine
    import lightgbm_tpu.basic as basic
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, _wrap(full), 5, verbose_eval=False)
    acc = ((bst.predict(X) > 0.5) == y).mean()
    assert acc > 0.9


def _wrap(inner):
    d = lgb.Dataset(None, free_raw_data=False)
    d._inner = inner
    return d


def test_parse_machine_list(tmp_path):
    cfg = lgb.Config({"machines": "10.0.0.1:500,10.0.0.2:500"})
    assert parse_machine_list(cfg) == ["10.0.0.1:500", "10.0.0.2:500"]
    p = tmp_path / "mlist.txt"
    p.write_text("10.0.0.1 500\n10.0.0.2 500\n")
    cfg2 = lgb.Config({"machine_list_filename": str(p)})
    assert parse_machine_list(cfg2) == ["10.0.0.1:500", "10.0.0.2:500"]
