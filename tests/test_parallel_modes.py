"""Voting- and feature-parallel learners vs serial on an 8-device CPU mesh.

Feature-parallel must be EXACT (data replicated; the shard-merged argmax has
the same tie semantics as the serial scan). Voting-parallel is exact when
2*top_k covers every feature (every feature wins the vote and is reduced);
with fewer votes it is the PV-tree approximation and only quality is
asserted — the same contract as the reference learner.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb

# every test here trains on an 8-virtual-device shard_map mesh; on the
# 2-core CPU CI host each is a 45-100s XLA compile, so the whole module
# rides in the slow tier (tier-1 budget)
pytestmark = pytest.mark.slow


def _data(n=4000, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    X[rng.random((n, f)) < 0.05] = np.nan
    y = (np.nan_to_num(X[:, 0]) + 0.5 * np.nan_to_num(X[:, 1]) > 0
         ).astype(float)
    return X, y


def _trees(bst):
    bst._booster._materialize_pending()
    return bst._booster.models


def _train(X, y, **extra):
    params = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
              "max_bin": 63}
    params.update(extra)
    return lgb.train(params, lgb.Dataset(X, y), 8, verbose_eval=False)


def _assert_same_structure(a, b):
    assert len(a) == len(b)
    for ta, tb in zip(a, b):
        assert ta.num_leaves == tb.num_leaves
        ni = ta.num_leaves - 1
        np.testing.assert_array_equal(ta.split_feature[:ni],
                                      tb.split_feature[:ni])
        np.testing.assert_array_equal(ta.threshold_in_bin[:ni],
                                      tb.threshold_in_bin[:ni])
        np.testing.assert_allclose(ta.leaf_value[:ta.num_leaves],
                                   tb.leaf_value[:tb.num_leaves],
                                   rtol=1e-5, atol=1e-7)


def test_feature_parallel_matches_serial():
    X, y = _data()
    serial = _train(X, y, tree_learner="serial")
    feat = _train(X, y, tree_learner="feature")
    _assert_same_structure(_trees(serial), _trees(feat))


def test_voting_parallel_full_vote_matches_data_parallel():
    X, y = _data()
    # 2 * top_k >= F: every feature is voted in, so the reduction covers the
    # full histogram. Trees match the data-parallel learner's up to f32
    # summation order (voting fixes histograms after the selective reduce,
    # data-parallel before the subtraction trick — a near-tie threshold can
    # legitimately flip), so assert feature-level structure + gain/output
    # closeness instead of bit equality.
    data = _train(X, y, tree_learner="data")
    vote = _train(X, y, tree_learner="voting", top_k=10)
    td, tv = _trees(data), _trees(vote)
    assert len(td) == len(tv)
    for a, b in zip(td, tv):
        assert a.num_leaves == b.num_leaves
    Xc = np.nan_to_num(X)
    pd_, pv = data.predict(Xc), vote.predict(Xc)
    # a near-tie threshold flip early in a tree changes that subtree, so
    # bit equality is not guaranteed; the models must agree functionally
    assert np.mean(np.abs(pd_ - pv)) < 5e-3
    assert ((pd_ > 0.5) == (pv > 0.5)).mean() > 0.995


def test_voting_parallel_small_vote_still_learns():
    X, y = _data(n=6000)
    vote = _train(X, y, tree_learner="voting", top_k=2)
    p = vote.predict(np.nan_to_num(X))
    assert (((p > 0.5) == y).mean()) > 0.9


def test_voting_parallel_matches_serial_quality():
    X, y = _data(n=5000, seed=4)
    serial = _train(X, y, tree_learner="serial")
    vote = _train(X, y, tree_learner="voting", top_k=3)
    Xc = np.nan_to_num(X)
    acc_s = ((serial.predict(Xc) > 0.5) == y).mean()
    acc_v = ((vote.predict(Xc) > 0.5) == y).mean()
    assert acc_v > acc_s - 0.02


@pytest.mark.parametrize("mode", ["voting", "feature", "data"])
def test_parallel_modes_partitioned_path(monkeypatch, mode):
    """Same checks through the payload-sorting (partitioned) grower, which
    distributed-scale runs always use (num_data >= PARTITION_MIN_ROWS)."""
    import lightgbm_tpu.parallel.learners as learners_mod
    import lightgbm_tpu.treelearner.serial as serial_mod
    monkeypatch.setattr(serial_mod, "PARTITION_MIN_ROWS", 100)
    monkeypatch.setattr(learners_mod, "PARTITION_MIN_ROWS", 100)
    X, y = _data(n=3000, seed=2)
    serial = _train(X, y, tree_learner="serial")
    par = _train(X, y, tree_learner=mode,
                 **({"top_k": 10} if mode == "voting" else {}))
    ts, tp = _trees(serial), _trees(par)
    assert len(ts) == len(tp)
    Xc = np.nan_to_num(X)
    ps, pp = serial.predict(Xc), par.predict(Xc)
    assert ((ps > 0.5) == (pp > 0.5)).mean() > 0.99
    if mode == "feature":
        _assert_same_structure(ts, tp)
