"""The jaxpr abstract-interpretation engine (analysis/dataflow.py).

Four layers, mirroring the PR 13 acceptance criteria:

* interval/error propagation pinned against HAND-COMPUTED bounds for
  add/mul/dot/cumsum/select chains (the formulas are part of the
  engine's contract: one roundoff per op at result magnitude, gamma_K
  accumulation for contractions);
* loop handling: exact unroll for short static scans, join-fixpoint
  convergence on a stable scan body, widening on a divergent one;
* the custom_jvp-f64-const regression (satellite fix): consts closed
  over through call primitives are invisible to an equation-output
  walk and MUST be reported by the const-aware engine;
* the quantization certificate for the [G, 256] histogram plane:
  the static split-gain bound must dominate an empirical max over
  1k random stochastically-quantized payloads at the same geometry.
"""
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.analysis import dataflow as df
from lightgbm_tpu.analysis import quant_audit as qa

F32 = jnp.float32
U32 = 2.0 ** -24        # f32 unit roundoff


def _mk(fn, *shapes):
    return jax.make_jaxpr(fn)(*[jax.ShapeDtypeStruct(s, F32)
                                for s in shapes])


# ---------------------------------------------------------------------------
# interval + error propagation vs hand-computed bounds
# ---------------------------------------------------------------------------

def test_add_mul_chain_hand_bounds():
    """x*y + x with x in [0,2], y in [-1,3]: mul lands in [-2,6] with
    one roundoff at magnitude 6; add lands in [-2,8] adding the
    propagated error plus one roundoff at magnitude 8."""
    closed = _mk(lambda x, y: x * y + x, (4,), (4,))
    rep = df.interpret(closed, in_ranges={0: (0.0, 2.0), 1: (-1.0, 3.0)})
    out = rep.out_vals[0]
    assert (out.rng.lo, out.rng.hi) == (-2.0, 8.0)
    assert out.err == pytest.approx(U32 * 6 + U32 * 8)


def test_sub_select_chain_hand_bounds():
    """where(m, x - y, x) joins both branches; select is exact so the
    error is the max of the branch errors."""
    def fn(m, x, y):
        return jnp.where(m, x - y, x)
    closed = jax.make_jaxpr(fn)(
        jax.ShapeDtypeStruct((4,), jnp.bool_),
        jax.ShapeDtypeStruct((4,), F32),
        jax.ShapeDtypeStruct((4,), F32))
    rep = df.interpret(closed, in_ranges={1: (0.0, 1.0), 2: (0.0, 4.0)})
    out = rep.out_vals[0]
    # sub: [-4, 1] (err u*4); join with x: [-4, 1]
    assert (out.rng.lo, out.rng.hi) == (-4.0, 1.0)
    assert out.err == pytest.approx(U32 * 4)


def test_dot_hand_bounds():
    """[2,8]x[8,3] contraction (K=8) of a in [0,1], b in [-1,1]:
    range K*hull(a*b) = [-8,8], error K*u*|a||b| for exact inputs."""
    def fn(a, b):
        return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                   preferred_element_type=F32)
    closed = _mk(fn, (2, 8), (8, 3))
    rep = df.interpret(closed, in_ranges={0: (0.0, 1.0), 1: (-1.0, 1.0)})
    out = rep.out_vals[0]
    assert (out.rng.lo, out.rng.hi) == (-8.0, 8.0)
    assert out.err == pytest.approx(8 * U32)


def test_cumsum_hand_bounds():
    """cumsum of 16 values in [0,1]: partial sums live in [0,16];
    error is L*u at the output magnitude (gamma_L-style)."""
    closed = _mk(lambda x: jnp.cumsum(x), (16,))
    rep = df.interpret(closed, in_ranges={0: (0.0, 1.0)})
    out = rep.out_vals[0]
    assert (out.rng.lo, out.rng.hi) == (0.0, 16.0)
    assert out.err == pytest.approx(16 * U32 * 16)


def test_div_needs_nonzero_denominator():
    """x / h is bounded only when the denominator interval excludes
    zero — the split-gain H + lambda pattern."""
    closed = _mk(lambda x, h: x / (h + jnp.float32(1.0)), (4,), (4,))
    rep = df.interpret(closed, in_ranges={0: (-8.0, 8.0), 1: (0.0, 3.0)})
    out = rep.out_vals[0]
    assert (out.rng.lo, out.rng.hi) == (-8.0, 8.0)
    closed2 = _mk(lambda x, h: x / h, (4,), (4,))
    rep2 = df.interpret(closed2, in_ranges={0: (-8.0, 8.0),
                                            1: (-1.0, 3.0)})
    assert not rep2.out_vals[0].rng.bounded


def test_clamp_interval_sound_for_nonpoint_bounds():
    """clamp with a data-dependent upper bound: the result can land at
    the BOUND's low end, so [5,5] clamped into hi in [0,10] must
    include 0 — the monotone min/max formula, not a point-bound
    shortcut."""
    def fn(x, hi):
        return jax.lax.clamp(jnp.float32(0.0), x, hi)
    closed = _mk(fn, (4,), (4,))
    rep = df.interpret(closed, in_ranges={0: (5.0, 5.0),
                                          1: (0.0, 10.0)})
    out = rep.out_vals[0]
    assert out.rng.lo == 0.0 and out.rng.hi == 5.0


def test_integer_pow_negative_and_zero_exponents():
    """x ** -2 with x in [2,4] is [1/16, 1/4]; x**0 is exactly 1; a
    zero-straddling base under a negative power must degrade to TOP,
    never return the base's range unchanged."""
    closed = _mk(lambda x: x ** -2, (4,))
    rep = df.interpret(closed, in_ranges={0: (2.0, 4.0)})
    out = rep.out_vals[0]
    assert out.rng.lo == pytest.approx(1.0 / 16.0)
    assert out.rng.hi == pytest.approx(1.0 / 4.0)
    closed0 = _mk(lambda x: x ** 0, (4,))
    rep0 = df.interpret(closed0, in_ranges={0: (2.0, 4.0)})
    assert (rep0.out_vals[0].rng.lo, rep0.out_vals[0].rng.hi) \
        == (1.0, 1.0)
    rep_bad = df.interpret(closed, in_ranges={0: (-1.0, 4.0)})
    assert not rep_bad.out_vals[0].rng.bounded


def test_unknown_primitive_degrades_to_top():
    """Soundness: a primitive without a rule must produce TOP, never a
    fabricated bound (sort has no transfer rule)."""
    closed = _mk(lambda x: jnp.sort(x), (8,))
    rep = df.interpret(closed, in_ranges={0: (0.0, 1.0)})
    assert not rep.out_vals[0].rng.bounded or \
        rep.out_vals[0].rng == df.Interval(0.0, 1.0)


# ---------------------------------------------------------------------------
# loop bodies: exact unroll / fixpoint / widening
# ---------------------------------------------------------------------------

def _scan_prog(body, length, init=0.0):
    return jax.make_jaxpr(
        lambda xs: jax.lax.scan(body, jnp.float32(init), xs))(
            jax.ShapeDtypeStruct((length,), F32))


def test_scan_short_unrolls_exactly():
    """An additive carry over 8 steps of x in [0,1] proves the TIGHT
    bound [0,8] — short static scans are unrolled, not widened."""
    closed = _scan_prog(lambda c, x: (c + x, c), 8)
    rep = df.interpret(closed, in_ranges={0: (0.0, 1.0)})
    assert (rep.out_vals[0].rng.lo, rep.out_vals[0].rng.hi) == (0.0, 8.0)
    assert rep.fixpoint == {"rounds": 8, "converged": True,
                            "widened": False, "mode": "unrolled"}


def test_scan_fixpoint_converges_on_stable_body():
    """max(c, x) saturates at the element bound: the join-fixpoint
    reaches [0,1] in two rounds with no widening, on a scan far too
    long to unroll."""
    closed = _scan_prog(lambda c, x: (jnp.maximum(c, x), c), 4096)
    rep = df.interpret(closed, in_ranges={0: (0.0, 1.0)})
    assert (rep.out_vals[0].rng.lo, rep.out_vals[0].rng.hi) == (0.0, 1.0)
    assert rep.fixpoint["mode"] == "fixpoint"
    assert rep.fixpoint["converged"] and not rep.fixpoint["widened"]
    assert rep.fixpoint["rounds"] <= 3


def test_scan_divergent_body_widens():
    """An additive carry over 4096 steps cannot stabilize: widening
    must fire and the upper bound goes to +inf (soundly — never a
    fabricated finite bound), within the iteration cap."""
    closed = _scan_prog(lambda c, x: (c + x, c), 4096)
    rep = df.interpret(closed, in_ranges={0: (0.0, 1.0)})
    out = rep.out_vals[0]
    assert out.rng.lo == 0.0 and out.rng.hi == math.inf
    assert rep.fixpoint["widened"]
    assert rep.fixpoint["rounds"] <= df.FIXPOINT_MAX


def test_while_carry_fixpoint():
    closed = jax.make_jaxpr(
        lambda x: jax.lax.while_loop(
            lambda c: c[0] < 10,
            lambda c: (c[0] + 1, jnp.minimum(c[1], jnp.float32(0.0))),
            (jnp.int32(0), x)))(jax.ShapeDtypeStruct((), F32))
    rep = df.interpret(closed, in_ranges={0: (-2.0, 5.0)})
    # min-carry saturates at [-2, 0] joined with the seed [-2, 5]
    assert rep.out_vals[1].rng.lo == -2.0
    assert rep.out_vals[1].rng.hi <= 5.0


# ---------------------------------------------------------------------------
# narrowing sites + the custom_jvp f64-const regression
# ---------------------------------------------------------------------------

def test_narrow_site_range_proven():
    def fn(x):
        return (x * jnp.float64(0.5)).astype(F32)
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8,), jnp.float64))
    rep = df.interpret(closed, in_ranges={0: (-1000.0, 1000.0)})
    (site,) = [s for s in rep.narrowings if not s.weak_src]
    assert site.src == "float64" and site.dst == "float32"
    assert site.fits and not site.decision_relevant
    assert (site.rng.lo, site.rng.hi) == (-500.0, 500.0)


def test_narrow_site_feeding_compare_is_decision_relevant():
    def fn(x):
        g32 = x.astype(F32)
        return jnp.max(g32)
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8,), jnp.float64))
    rep = df.interpret(closed, in_ranges={0: (0.0, 1.0)})
    (site,) = [s for s in rep.narrowings if not s.weak_src]
    assert site.decision_relevant        # the tie-flip geometry


def test_narrow_decision_relevance_crosses_pjit():
    """The tie-flip geometry hidden behind a jit boundary: the compare
    lives inside the callee, the narrowing outside — the site key must
    thread through the pjit call and still mark the site."""
    def fn(x):
        g32 = x.astype(F32)
        return jax.jit(lambda y: jnp.argmax(y))(g32)
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8,), jnp.float64))
    rep = df.interpret(closed, in_ranges={0: (-10.0, 10.0)})
    (site,) = [s for s in rep.narrowings if not s.weak_src]
    assert site.decision_relevant


def test_custom_jvp_f64_const_is_found():
    """The satellite regression: an f64 const closed over inside a
    custom_jvp body, narrowed before use — no equation outputs f64
    (beyond benign staging), yet the const IS f64 data in the program.
    The const-aware engine and find_f64_consts must both see it."""
    from lightgbm_tpu.analysis.jaxpr_audit import (
        _audit_jaxpr, build_custom_jvp_f64_fixture)
    closed = build_custom_jvp_f64_fixture()
    assert df.find_f64_consts(closed)
    rep = df.interpret(closed)
    assert any("const f64" in s for s in rep.f64_sites)
    res = _audit_jaxpr("fixture", closed, strict_f64=True)
    assert not res.ok and "const f64" in res.detail


def test_f64_const_through_pjit_is_found():
    c64 = np.linspace(0.0, 1.0, 5)          # float64

    def fn(x):
        return jax.jit(lambda v: v * jnp.asarray(c64).astype(F32))(x)
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((5,), F32))
    assert df.find_f64_consts(closed)


def test_alias_sites_query():
    """The donation query the persist audits now use: pjit donation
    shows up as input_output_aliases on the traced call."""
    @jax.jit
    def fn(x):
        return x * jnp.float32(2.0)
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((4,), F32))
    assert isinstance(df.alias_sites(closed.jaxpr), list)


# ---------------------------------------------------------------------------
# quantization certificate: static bound vs 1k-payload empirical max
# ---------------------------------------------------------------------------

def _stochastic_quantize(plane, scale, bits, rng):
    """Reference stochastic-rounding quantizer: symmetric at the
    contract scale, unbiased, per-entry error <= step."""
    levels = (1 << bits) - 2
    step = 2.0 * scale / levels
    q = np.floor(plane / step + rng.random(plane.shape))
    return np.clip(q, -(levels // 2 + 1), levels // 2) * step


def _split_gains(g, h, lam):
    """gain(s) = GL^2/(HL+lam) + GR^2/(HR+lam) - GP^2/(HP+lam) over
    every split point of a [W] plane pair."""
    gl, hl = np.cumsum(g)[:-1], np.cumsum(h)[:-1]
    gp, hp = g.sum(), h.sum()
    gr, hr = gp - gl, hp - hl
    return (gl ** 2 / (hl + lam) + gr ** 2 / (hr + lam)
            - gp ** 2 / (hp + lam))


@pytest.mark.parametrize("geometry", ["higgs", "expo"])
def test_quant_certificate_geometries(geometry):
    """The shipped certificates: int16 histogram planes at both
    geometries certify under the pinned split-decision budget."""
    certs = {c["spec"]["name"]: c for c in qa.compute_artifact()}
    cert = certs["hist_int16_%s" % geometry]
    assert cert["ok"]
    assert cert["bound"] <= qa.SPLIT_DECISION_BUDGET
    assert cert["margin"] > 1.5
    # int8 at the same geometry must NOT certify
    spec8 = dict(cert["spec"], target="int8", name="hist_int8")
    assert not qa.certify(spec8)["ok"]


def test_quant_bound_dominates_empirical_max():
    """1000 random [2, 256] plane payloads, R ranks, int16 stochastic
    rounding at the contract scales: the worst observed split-gain
    perturbation over the certified decision domain must stay below
    the static bound (with real margin — the bound is a 6.5-sigma
    Hoeffding envelope)."""
    W, R, rows, lam = 256, 4, 65536, 1.0
    g_max, h_max = 1.0, 0.25
    spec = {"name": "emp", "kind": "histogram", "target": "int16",
            "stochastic": True, "rows_per_rank": rows, "ranks": R,
            "bins": W, "g_max": g_max, "h_max": h_max, "lambda": lam}
    cert = qa.certify(spec)
    assert cert["ok"]
    s_g, s_h = rows * g_max, rows * h_max
    h_floor = qa.H_CHILD_FRAC * R * s_h
    rng = np.random.default_rng(20260804)
    worst = 0.0
    n_checked = 0
    for _ in range(1000):
        g_ranks = rng.uniform(-1.0, 1.0, (R, W))
        g_ranks *= s_g / np.abs(g_ranks).sum(axis=1, keepdims=True)
        h_ranks = rng.uniform(0.0, 1.0, (R, W))
        h_ranks *= s_h / h_ranks.sum(axis=1, keepdims=True)
        gq = sum(_stochastic_quantize(g_ranks[r], s_g, 16, rng)
                 for r in range(R))
        hq = sum(_stochastic_quantize(h_ranks[r], s_h, 16, rng)
                 for r in range(R))
        g, h = g_ranks.sum(axis=0), h_ranks.sum(axis=0)
        exact = _split_gains(g, h, lam)
        quant = _split_gains(gq, hq, lam)
        hl = np.cumsum(h)[:-1]
        in_domain = (hl >= h_floor) & ((h.sum() - hl) >= h_floor)
        if in_domain.any():
            worst = max(worst,
                        float(np.abs(exact - quant)[in_domain].max()))
            n_checked += int(in_domain.sum())
    assert n_checked > 1000          # the domain is actually exercised
    assert worst <= cert["gain_perturbation"]
    assert worst > 0.0               # and the experiment is non-trivial


def test_leaf_f16_certificate_tracks_ensemble():
    from lightgbm_tpu.predict.compile import quant_spec
    cert = qa.certify(quant_spec())
    assert cert["ok"] and cert["bound"] == pytest.approx(2.0 ** -11)
    # a bf16 leaf spec keeps only 8 bits and must fail the budget
    assert not qa.certify(dict(quant_spec(), target="bfloat16"))["ok"]


def test_input_contract_annotations_exist():
    """The seeder's contract surface: every annotated module exposes
    ranges the auditors read (hessians nonnegative, bins below w)."""
    from lightgbm_tpu.ops.grow_persist import persist_input_contract
    from lightgbm_tpu.ops.pallas_grow import grow_input_contract
    from lightgbm_tpu.ops.pallas_histogram import hist_input_contract
    from lightgbm_tpu.ops.pallas_scan import scan_input_contract
    hc = hist_input_contract(w=256, rows=1000)
    assert hc["bins_t"] == (0.0, 255.0) and hc["hess"][0] == 0.0
    pc = persist_input_contract(n=1000)
    assert pc["hess"][0] == 0.0 and pc["counts"] == (0.0, 1000.0)
    sc = scan_input_contract(rows=1000)
    assert sc["hb"][0] == 0.0
    gc = grow_input_contract(NP=4096)
    assert gc["plan_rows"] == (-1.0, 4096.0)


def test_dataflow_values_counter():
    from lightgbm_tpu.telemetry import events
    prev = events.mode()
    events.enable("timers")
    events.reset()
    try:
        closed = _mk(lambda x: x + x, (4,))
        df.interpret(closed)
        counts = events.counts_snapshot()
        assert counts.get("analysis::dataflow_values", 0) >= 1
    finally:
        events.reset()
        if prev == events.OFF:
            events.disable()
