"""Elastic resume onto a different mesh size (ISSUE 12 tentpole).

The acceptance contract:
  * a run trained on world=2, killed at iteration K (``kill@``/
    ``resize@``), resumed on world=1 produces a final model BIT-EXACT
    with the uninterrupted run — scores reseed from the restored model,
    binning comes from the mesh manifest, and the bagging/GOSS draws
    hash dataset-GLOBAL row ids, all mesh-size invariant;
  * a world=4 rank finds the same snapshot generation and its row slice
    through the same manifest;
  * the layout algebra (old shards -> global rows -> new shards) round-
    trips exactly;
  * a world-size change is recognized as THIS run needing reshard, not
    silently treated as a foreign run (fresh start, work lost).

The real two-process world=2 -> world=1 chaos run is the slow-tier
sibling in tests/test_chaos.py; everything here is single-process
tier-1.
"""
import os
import shutil

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import engine
from lightgbm_tpu.resilience import reshard, restore
from lightgbm_tpu.resilience.checkpoint import (CheckpointWriter,
                                                array_fingerprint,
                                                config_hash,
                                                list_checkpoints,
                                                load_checkpoint)
from lightgbm_tpu.resilience.faults import TrainingResized
from lightgbm_tpu.utils.log import LightGBMError


def _make_binary(n=600, nf=5, seed=5):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, nf))
    y = (X[:, 0] - 0.5 * X[:, 2] + rng.normal(size=n) * 0.3 > 0)
    return X, y.astype(float)


def _fresh_dir(tmp_path, name):
    d = str(tmp_path / name)
    shutil.rmtree(d, ignore_errors=True)
    os.makedirs(d)
    return d


# the distributed-driver params: bagging mid-stream so the resume has
# real RNG state to keep; num_machines=1 runs the SAME sharded driver
# single-process (the small end of the elastic family)
PARAMS = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
          "min_data_in_leaf": 5, "learning_rate": 0.3,
          "bagging_fraction": 0.8, "bagging_freq": 2,
          "snapshot_freq": 4, "num_machines": 1}


def _global_fp(X, y):
    return array_fingerprint(np.ascontiguousarray(X, np.float64),
                             np.asarray(y, np.float64))


def _dtrain(params, X, y, rounds=12):
    """engine._train_distributed with the run-scoped configuration
    engine.train would install (fault plan, retry policy) — the tests
    drive the world=1 end of the driver directly."""
    from lightgbm_tpu.resilience import faults, retry
    cfg = lgb.Config(dict(params))
    faults.configure_from_config(cfg)
    retry.configure_from_config(cfg)
    try:
        return engine._train_distributed(dict(params), lgb.Dataset(X, y),
                                         rounds, None)
    finally:
        faults.reset()


def _fabricate_world2(d, model_text, iteration, cfg, gfp, manifest):
    """Rewrite `d` as the post-kill state of a world=2 run at
    `iteration`: two rank-tagged model snapshots + a world=2 manifest —
    byte-wise exactly what two ranks of a 2-host mesh leave behind
    (every rank's model text is identical by construction)."""
    shutil.rmtree(d)
    os.makedirs(d)
    for rank in range(2):
        writer = CheckpointWriter(d, keep=3, cfg_hash=config_hash(cfg),
                                  rank=rank,
                                  fingerprint="shard-of-rank-%d" % rank,
                                  global_fingerprint=gfp, world=2)
        writer.write_model_text(model_text, iteration,
                                extra_meta={"n_init": 0})
    man2 = dict(manifest)
    man2["world"] = 2
    reshard.ensure_manifest(d, man2)


# ---------------------------------------------------------------------------
# the acceptance pin: world=2 state, killed at K, resumed on world=1
# and probed from world=4 — bit-exact vs the uninterrupted run
# ---------------------------------------------------------------------------

def test_elastic_resume_parity(tmp_path):
    X, y = _make_binary()
    d = _fresh_dir(tmp_path, "elastic")
    params = dict(PARAMS, checkpoint_dir=d)

    # (a) uninterrupted reference through the distributed driver
    ref = _dtrain(params, X, y)
    model_ref = ref.model_to_string(num_iteration=-1)
    manifest = reshard.load_manifest(d)
    assert manifest is not None and manifest["world"] == 1
    assert manifest["assignment"] == "round_robin"

    # (b) same run, resized (pod shrink) before iteration 8: the resize
    # verb kills like a preemption but names the mesh it resumes on
    shutil.rmtree(d)
    os.makedirs(d)
    resized = dict(params, tpu_fault_plan="resize@iter=8;world=1")
    with pytest.raises(TrainingResized) as exc:
        _dtrain(resized, X, y)
    assert exc.value.target_world == 1
    assert "resumable at iteration <= 8" in str(exc.value)
    snaps = [i for i, _ in list_checkpoints(d, 0)]
    assert snaps == [4, 8]
    _meta8, arr8 = load_checkpoint(
        [p for i, p in list_checkpoints(d, 0) if i == 8][0])
    model8 = arr8["model_text"].tobytes().decode()

    # (c) rewrite the directory as the equivalent WORLD=2 post-kill
    # state (rank-tagged shards + world=2 manifest)
    cfg = lgb.Config(dict(params))
    gfp = _global_fp(X, y)
    _fabricate_world2(d, model8, 8, cfg, gfp, manifest)

    # (d) a world=4 rank (none of whose own rank files exist) finds the
    # same snapshot generation through the manifest and knows its slice
    cfg4 = lgb.Config(dict(params, num_machines=4))
    found4 = reshard.find_elastic(cfg4, rank=3, world=4, global_fp=gfp)
    assert found4 is not None
    it4, text4, meta4, man4 = found4
    assert it4 == 8 and text4 == model8 and meta4["world"] == 2
    np.testing.assert_array_equal(reshard.slice_for_rank(man4, 3, 4),
                                  np.arange(3, len(X), 4))

    # (e) elastic resume onto world=1 through the PUBLIC API: plain
    # lgb.train with num_machines unset routes into the distributed
    # driver via the manifest and finishes bit-exact vs (a)
    resume_params = {k: v for k, v in params.items()
                     if k != "num_machines"}
    res = lgb.train(resume_params, lgb.Dataset(X, y), 12,
                    verbose_eval=False)
    assert res.num_trees() == 12
    assert res.model_to_string(num_iteration=-1) == model_ref
    # the directory now describes its newest generation: world=1
    assert reshard.load_manifest(d)["world"] == 1


@pytest.mark.slow  # tier-1 870s budget: cheaper sibling tests cover this area
def test_same_mesh_kill_resume_through_driver(tmp_path):
    """The distributed driver's own kill/resume at world=1 (the
    degenerate mesh) stays bit-exact — the baseline the elastic path
    builds on."""
    X, y = _make_binary()
    d = _fresh_dir(tmp_path, "same")
    params = dict(PARAMS, checkpoint_dir=d)
    model_a = _dtrain(params, X, y).model_to_string(num_iteration=-1)
    shutil.rmtree(d)
    os.makedirs(d)
    killed = dict(params, tpu_fault_plan="kill@iter=8")
    with pytest.raises(lgb.basic.LightGBMError):
        _dtrain(killed, X, y)
    res = _dtrain(params, X, y)
    assert res.model_to_string(num_iteration=-1) == model_a


# ---------------------------------------------------------------------------
# layout algebra: old shards -> global -> new shards, exactly
# ---------------------------------------------------------------------------

def test_reshard_roundtrip_rows():
    man = reshard.build_manifest("cfg", "fp", world=3, n_rows=101,
                                 mappers=[])
    rng = np.random.default_rng(0)
    state = rng.normal(size=(101, 2))
    shards = [state[reshard.slice_for_rank(man, r, 3)] for r in range(3)]
    back = reshard.assemble_global(man, shards)
    np.testing.assert_array_equal(back, state)
    # re-slice for a LARGER mesh covers every row exactly once
    seen = np.concatenate([reshard.slice_for_rank(man, r, 5)
                           for r in range(5)])
    np.testing.assert_array_equal(np.sort(seen), np.arange(101))
    np.testing.assert_array_equal(
        reshard.reslice_local(man, back, 2, 5), state[2::5])


def test_reshard_roundtrip_queries():
    sizes = [4, 7, 2, 9, 5, 3, 6]
    n = sum(sizes)
    man = reshard.build_manifest("cfg", "fp", world=2, n_rows=n,
                                 mappers=[], assignment="query_blocks",
                                 group_sizes=sizes)
    state = np.arange(n, dtype=np.float64)
    shards = [state[reshard.slice_for_rank(man, r, 2)] for r in range(2)]
    np.testing.assert_array_equal(reshard.assemble_global(man, shards),
                                  state)
    # queries never split, any world: each rank's slice is contiguous
    # and the union is a partition of the row range
    for world in (1, 2, 3):
        slices = [reshard.slice_for_rank(man, r, world)
                  for r in range(world)]
        for s in slices:
            if len(s):
                np.testing.assert_array_equal(s, np.arange(s[0],
                                                           s[-1] + 1))
        np.testing.assert_array_equal(np.sort(np.concatenate(slices)),
                                      np.arange(n))


def test_reshard_refuses_pre_partition(tmp_path):
    man = reshard.build_manifest("cfg", "fp", world=2, n_rows=10,
                                 mappers=[], assignment="pre_partition")
    with pytest.raises(LightGBMError):
        reshard.slice_for_rank(man, 0, 4)
    # ... and the real resume path refuses the same way, loudly
    d = _fresh_dir(tmp_path, "prepart")
    cfg = lgb.Config(dict(PARAMS, checkpoint_dir=d))
    man2 = reshard.build_manifest(config_hash(cfg), "gfp", world=2,
                                  n_rows=10, mappers=[],
                                  assignment="pre_partition")
    reshard.ensure_manifest(d, man2)
    with pytest.raises(LightGBMError) as exc:
        reshard.find_elastic(cfg, 0, 1, "gfp")
    assert "pre-partitioned" in str(exc.value)


def test_assemble_global_validates_shapes():
    man = reshard.build_manifest("cfg", "fp", world=2, n_rows=10,
                                 mappers=[])
    with pytest.raises(LightGBMError):
        reshard.assemble_global(man, [np.zeros(5)])        # world mismatch
    with pytest.raises(LightGBMError):
        reshard.assemble_global(man, [np.zeros(5), np.zeros(3)])


# ---------------------------------------------------------------------------
# manifest mechanics
# ---------------------------------------------------------------------------

def test_manifest_roundtrip_and_identity(tmp_path):
    d = _fresh_dir(tmp_path, "man")
    man = reshard.build_manifest("cfgh", "gfp", world=2, n_rows=50,
                                 mappers=[])
    assert reshard.ensure_manifest(d, man)
    assert not [n for n in os.listdir(d) if n.endswith(".tmp")]
    back = reshard.load_manifest(d)
    assert back == man
    assert reshard.manifest_crc(back) == reshard.manifest_crc(man)
    # identical identity -> no rewrite; changed world -> rewrite
    assert not reshard.ensure_manifest(d, dict(man))
    man4 = dict(man, world=4)
    assert reshard.ensure_manifest(d, man4)
    assert reshard.load_manifest(d)["world"] == 4
    # matching predicate
    assert reshard.manifest_matches(man, "cfgh", "gfp")
    assert reshard.manifest_matches(man, "cfgh")          # fp optional
    assert not reshard.manifest_matches(man, "other", "gfp")
    assert not reshard.manifest_matches(man, "cfgh", "other")
    assert not reshard.manifest_matches(None, "cfgh")
    # an unparseable manifest is ignored, not fatal
    with open(reshard.manifest_path(d), "w") as f:
        f.write("{not json")
    assert reshard.load_manifest(d) is None


def test_find_elastic_edges(tmp_path):
    X, y = _make_binary(n=80)
    d = _fresh_dir(tmp_path, "edges")
    cfg = lgb.Config(dict(PARAMS, checkpoint_dir=d))
    gfp = _global_fp(X, y)
    # no manifest -> None
    assert reshard.find_elastic(cfg, 0, 1, gfp) is None
    # matching manifest, same world -> None (ordinary resume path)
    man = reshard.build_manifest(config_hash(cfg), gfp, world=1,
                                 n_rows=len(X), mappers=[])
    reshard.ensure_manifest(d, man)
    assert reshard.find_elastic(cfg, 0, 1, gfp) is None
    # different world but no restorable snapshot -> None (fresh start)
    man2 = dict(man, world=2)
    reshard.ensure_manifest(d, man2)
    assert reshard.find_elastic(cfg, 0, 1, gfp) is None
    # foreign dataset -> manifest ignored
    assert reshard.find_elastic(cfg, 0, 1, "feedface") is None
    # a corrupt newest shard falls back to the older generation
    writer = CheckpointWriter(d, keep=3, cfg_hash=config_hash(cfg),
                              rank=0, fingerprint="s0",
                              global_fingerprint=gfp, world=2)
    writer.write_model_text("model four", 4)
    writer.write_model_text("model eight", 8)
    newest = [p for i, p in list_checkpoints(d, 0) if i == 8][0]
    with open(newest, "r+b") as f:
        f.seek(-8, os.SEEK_END)
        f.write(b"\x00" * 8)
    found = reshard.find_elastic(cfg, 0, 1, gfp)
    assert found is not None and found[0] == 4
    assert found[1] == "model four"


# ---------------------------------------------------------------------------
# the fingerprint-split satellite: a world-size change is THIS run
# needing reshard, never a silent foreign-run fresh start
# ---------------------------------------------------------------------------

def test_world_change_without_manifest_raises_not_fresh(tmp_path):
    X, y = _make_binary(n=80)
    d = _fresh_dir(tmp_path, "nofan")
    cfg = lgb.Config(dict(PARAMS, checkpoint_dir=d))
    gfp = _global_fp(X, y)
    # snapshots written by a world=2 run (shard-local fingerprints of
    # ITS shards), manifest lost
    writer = CheckpointWriter(d, keep=3, cfg_hash=config_hash(cfg),
                              rank=0, fingerprint="old-world-shard",
                              global_fingerprint=gfp, world=2)
    writer.write_model_text("m", 4)
    shard = X[0::1], y  # this (world=1) rank's shard fingerprint differs
    with pytest.raises(LightGBMError) as exc:
        restore.find_distributed(cfg, 0, *shard, global_fp=gfp)
    assert "mesh manifest" in str(exc.value)
    # a genuinely foreign dataset (global fingerprint differs too) still
    # starts fresh silently — that behavior is load-bearing
    assert restore.find_distributed(cfg, 0, *shard,
                                    global_fp="feedface") is None
