"""Benchmark: HIGGS-like GBDT training throughput vs the reference CPU anchor.

Reference anchor (BASELINE.md / docs/Experiments.rst:103-117): LightGBM
trains HIGGS (10.5M rows x 28 features, binary, 500 iterations, 255 leaves,
max_bin=255 defaults) in 238.5 s on 2x E5-2670v3 => 22.01M row-iterations/s.

This bench trains the same shape of problem (synthetic HIGGS-like data —
the real set needs a download; zero egress here) on whatever accelerator
jax exposes and reports row-iterations/s relative to that anchor.
Rows/iters scale via BENCH_ROWS / BENCH_ITERS env vars; the metric is
throughput so partial runs compare fairly.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import os
import sys
import time

REF_ROWS = 10_500_000
REF_ITERS = 500
REF_SECONDS = 238.5
REF_THROUGHPUT = REF_ROWS * REF_ITERS / REF_SECONDS   # 22.01M row-iters/s


# canonical generator lives in the package (shared with the profiling CLI
# and tests); re-exported here for bench_full / prof_* imports
from lightgbm_tpu.data.synth import make_higgs_like  # noqa: E402,F401


BENCH_SCHEMA_VERSION = 1


def _phase_stats(telemetry, work=None):
    """One phase's telemetry snapshot + the archived roofline card —
    the shared layout lives in telemetry/perfmodel.phase_snapshot (the
    profile CLI archives the identical structure)."""
    from lightgbm_tpu.telemetry import perfmodel
    return perfmodel.phase_snapshot(work=work)


def _git_sha():
    import subprocess
    try:
        return subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10,
        ).stdout.strip()
    except Exception:
        return ""


def build_meta(repeats=1, spread=None):
    """The self-describing ``meta`` block every recorded round carries:
    schema version, git SHA, device profile, jax version, the active
    BENCH_* knobs, and the median-of-k repeat count + per-key relative
    spread. Rounds become comparable ARTIFACTS instead of bare numbers —
    the perf sentinel (analysis/perf_gate.py) keys its comparability
    lineages and noise bands off exactly this block."""
    import platform

    import jax
    from lightgbm_tpu.telemetry.devices import detect_profile
    try:
        devs = jax.devices()
        kind, plat, count = devs[0].device_kind, devs[0].platform, len(devs)
    except Exception:
        kind, plat, count = "unknown", "unknown", 0
    return {
        "schema": BENCH_SCHEMA_VERSION,
        "git_sha": _git_sha(),
        "device": {"kind": kind, "platform": plat, "count": count,
                   "profile": detect_profile().to_dict()},
        "jax": jax.__version__,
        "python": platform.python_version(),
        "knobs": {k: v for k, v in sorted(os.environ.items())
                  if k.startswith("BENCH")},
        "repeats": int(repeats),
        "spread": {k: round(float(v), 4)
                   for k, v in sorted((spread or {}).items())},
    }


def _median_merge(runs):
    """Element-wise median of repeated phase dicts + per-key relative
    spread ((max-min)/|median|) for the numeric keys present in every
    run. Non-numeric / unstable keys keep the first run's value."""
    import statistics
    merged = dict(runs[0])
    spread = {}
    for k, v0 in runs[0].items():
        if isinstance(v0, bool) or not isinstance(v0, (int, float)):
            continue
        vals = [r[k] for r in runs
                if isinstance(r.get(k), (int, float))
                and not isinstance(r.get(k), bool)]
        if len(vals) != len(runs):
            continue
        med = statistics.median(vals)
        merged[k] = med if isinstance(v0, int) and med == int(med) \
            else round(float(med), 6)
        spread[k] = (max(vals) - min(vals)) / max(abs(med), 1e-12)
    return merged, spread


def _repeat_phase(fn, repeats, reset=None):
    """(median-merged phase dict, per-key spread) over `repeats` runs.

    ``reset`` (telemetry.reset when telemetry is on) runs before EVERY
    repeat so the phase snapshot taken afterwards covers the LAST run
    only — without it, repeated phases would archive k runs' accumulated
    wall against a single run's work geometry, and the roofline card
    would divide a 1-run model by a k-run denominator."""
    runs = []
    for _ in range(max(repeats, 1)):
        if reset is not None:
            reset()
        runs.append(fn())
    if len(runs) == 1:
        return runs[0], {}
    return _median_merge(runs)


def _copy_spread(spread_out, phase_spread, mapping=None, **kw):
    """Record a phase's per-key spread under the BENCH result key names
    (``meta.spread`` speaks the same vocabulary as ``parsed``).
    ``mapping`` takes src keys that are not identifiers (the predict
    phase's dotted ``poisson.p99`` style)."""
    for src, dst in dict(mapping or {}, **kw).items():
        if src in phase_spread:
            spread_out[dst] = phase_spread[src]


def _median_merge_nested(runs, subkeys):
    """Median-merge for phases returning nested dicts (predict): each
    named sub-dict medians element-wise; spreads come back keyed
    ``sub.key``. Top-level non-dict values keep the first run's."""
    merged = dict(runs[0])
    spread = {}
    for sub in subkeys:
        subruns = [r[sub] for r in runs if isinstance(r.get(sub), dict)]
        if len(subruns) != len(runs):
            continue
        m, s = _median_merge(subruns)
        merged[sub] = m
        for k, v in s.items():
            spread["%s.%s" % (sub, k)] = v
    return merged, spread


def _extra_params():
    """BENCH_PARAMS="k=v,k=v": extra training params merged into EVERY
    bench phase (e.g. ``tpu_persist_scan=force,num_leaves=63`` records
    a comparable round on a box without the default fast-path gates —
    the knob lands in meta.knobs, so such rounds open their own
    comparability lineage instead of polluting the default one)."""
    raw = os.environ.get("BENCH_PARAMS", "")
    out = {}
    for tok in raw.split(","):
        tok = tok.strip()
        if not tok:
            continue
        k, _, v = tok.partition("=")
        out[k.strip()] = v.strip()
    return out


def _phase_params(base):
    """One phase's params: the phase defaults + the BENCH_PARAMS knob."""
    p = dict(base)
    p.update(_extra_params())
    return p


def main():
    n_rows = int(os.environ.get("BENCH_ROWS", 10_500_000))
    n_iters = int(os.environ.get("BENCH_ITERS", 500))
    num_leaves = int(os.environ.get("BENCH_LEAVES", 255))
    max_bin = int(os.environ.get("BENCH_MAX_BIN", 255))

    import lightgbm_tpu as lgb
    from lightgbm_tpu import telemetry

    # phase attribution rides the telemetry registry (timers mode): the
    # snapshot records WHERE the time went, next to the throughput metric.
    # BENCH_TELEMETRY=0 opts out, measuring the headline number with zero
    # telemetry overhead inside the timed window (comparable with BENCH
    # rounds archived before the telemetry subsystem existed).
    bench_telemetry = os.environ.get("BENCH_TELEMETRY", "1") != "0"
    if bench_telemetry:
        telemetry.enable("timers")
    phase_snaps = {}
    # BENCH_REPEATS=k: run every timed phase k times, report the per-key
    # MEDIAN, and record the relative spread into meta.spread — the perf
    # sentinel widens its noise band to the recorded spread
    repeats = int(os.environ.get("BENCH_REPEATS", 1))
    spread_out = {}

    X, y = make_higgs_like(n_rows)
    t_bin0 = time.time()
    ds = lgb.Dataset(X, y)
    ds.construct()
    t_bin = time.time() - t_bin0

    params = _phase_params({"objective": "binary",
                            "num_leaves": num_leaves,
                            "max_bin": max_bin, "verbosity": -1,
                            "metric": "none"})
    num_leaves = int(params["num_leaves"])

    # warmup: compile the grower AND the fused 16-iteration scan on the
    # full-size problem (compiles are one-time costs; steady state is what
    # the throughput metric compares against the anchor)
    warm = lgb.train(dict(params), ds, 17, verbose_eval=False)
    warm._booster._materialize_pending()
    del warm

    def _timed_higgs():
        if bench_telemetry:   # opted out: never touch the global registry
            telemetry.reset()   # steady state: drop binning/warmup compiles
        t0 = time.time()
        booster = lgb.train(dict(params), ds, n_iters, verbose_eval=False)
        # force the async pipeline to finish: materialize every pending
        # device tree and block on the score buffer
        booster._booster._materialize_pending()
        import jax
        jax.block_until_ready(booster._booster.train_score.score_device(0))
        train_s = time.time() - t0
        throughput = n_rows * n_iters / train_s
        return {"train_s": train_s,
                "value": round(throughput / 1e6, 3),
                "vs_baseline": round(throughput / REF_THROUGHPUT, 4)}

    reset_fn = telemetry.reset if bench_telemetry else None
    higgs, higgs_spread = _repeat_phase(_timed_higgs, repeats,
                                        reset=reset_fn)
    train_s = higgs["train_s"]
    if bench_telemetry:
        phase_snaps["higgs"] = _phase_stats(
            telemetry, work={"phase": "higgs", "rows": n_rows,
                             "iters": n_iters, "num_leaves": num_leaves})
    _copy_spread(spread_out, higgs_spread, value="value",
                 vs_baseline="vs_baseline")

    result = {
        "metric": "higgs_like_train_throughput",
        "value": higgs["value"],
        "unit": "Mrow_iters_per_sec",
        "vs_baseline": higgs["vs_baseline"],
    }
    if bench_telemetry:
        result["phases"] = phase_snaps["higgs"]["categories"]
        # runtime numerics sentinel: the higgs phase's split-margin p01
        # (numerics::split_margin flushes when the persist path runs —
        # on a gate-less box use BENCH_PARAMS="tpu_persist_scan=force").
        # HIGHER_BETTER in the --perf sentinel: a quantization change
        # that collapses decision margins gates even at equal throughput
        mh = telemetry.histo.get("numerics::split_margin")
        if mh is not None and mh.count:
            # significant figures, not decimal places: the margin layout
            # reaches down to 1e-9 and a round(., 6) would flatten any
            # sub-5e-7 p01 to 0.0 — invisible to the HIGHER_BETTER gate
            result["margin_p01"] = float("%.4g" % mh.percentile(0.01))
    # print the primary metric BEFORE the MS-LTR phase so a hard crash
    # there (OOM kill, TPU fault) can't lose it; the combined line with
    # the ranking keys is re-printed last and shadows this one for
    # last-JSON-line parsers
    print(json.dumps(result), flush=True)
    print("# rows=%d iters=%d leaves=%d bins=%d train=%.1fs binning=%.1fs "
          "(ref anchor: %.1fM row-iters/s from HIGGS 238.5s)"
          % (n_rows, n_iters, num_leaves, max_bin, train_s, t_bin,
             REF_THROUGHPUT / 1e6), file=sys.stderr)
    ltr = None
    if os.environ.get("BENCH_SKIP_LTR", "") != "1":
        try:
            if bench_telemetry:
                telemetry.reset()
            ltr, ltr_spread = _repeat_phase(run_ltr, repeats, reset=reset_fn)
            if bench_telemetry:
                phase_snaps["ltr"] = _phase_stats(
                    telemetry, work={"phase": "ltr", "rows": ltr["rows"],
                                     "iters": ltr["iters"],
                                     "num_leaves":
                                         ltr.get("num_leaves", 255)})
            _copy_spread(spread_out, ltr_spread, value="ranking_value",
                         vs_baseline="ranking_vs_baseline")
        except Exception as exc:
            print("# MS-LTR phase failed: %r" % exc, file=sys.stderr)
    if ltr is not None:
        result["ranking_value"] = ltr["value"]
        result["ranking_vs_baseline"] = ltr["vs_baseline"]
        print(json.dumps(result), flush=True)
        print("# MS-LTR lambdarank: rows=%d iters=%d train=%.1fs -> "
              "%.2fM row-iters/s, vs anchor (2.27M*500/215.3s = 5.27M): "
              "%.4f" % (ltr["rows"], ltr["iters"], ltr["train_s"],
                        ltr["value"], ltr["vs_baseline"]), file=sys.stderr)
    expo = None
    if os.environ.get("BENCH_SKIP_EXPO", "") != "1":
        try:
            if bench_telemetry:
                telemetry.reset()
            expo, expo_spread = _repeat_phase(run_expo, repeats, reset=reset_fn)
            if bench_telemetry:
                phase_snaps["expo"] = _phase_stats(
                    telemetry, work={"phase": "expo",
                                     "rows": expo["rows"],
                                     "iters": expo["iters"],
                                     "num_leaves":
                                         expo.get("num_leaves", 255)})
            _copy_spread(spread_out, expo_spread, value="expo_value",
                         vs_baseline="expo_vs_baseline",
                         level_value="expo_level_value",
                         level_vs_baseline="expo_level_vs_baseline")
        except Exception as exc:
            print("# expo phase failed: %r" % exc, file=sys.stderr)
    if expo is not None:
        result["expo_value"] = expo["value"]
        result["expo_vs_baseline"] = expo["vs_baseline"]
        if "level_value" in expo:
            # level-program phase keys (PR 7): before/after for the
            # launch-overhead elimination, plus the measured launch count
            result["expo_level_value"] = expo["level_value"]
            result["expo_level_vs_baseline"] = expo["level_vs_baseline"]
            result["expo_level_programs"] = expo["level_programs"]
            result["expo_level_fallback_splits"] = \
                expo["level_fallback_splits"]
            result["expo_level_launches_per_tree"] = \
                expo["level_launches_per_tree"]
        if "launches_per_iter" in expo:
            # fused-iteration phase key (PR 17): device launches per
            # boosting iteration — the whole-iteration fusion target
            result["launches_per_iter"] = expo["launches_per_iter"]
        print(json.dumps(result), flush=True)
        print("# Expo-like EFB-bundled (%d groups for %d features): rows=%d "
              "iters=%d train=%.1fs -> %.2fM row-iters/s, vs anchor "
              "(11M*500/138.5s = 39.7M): %.4f"
              % (expo["groups"], expo["features"], expo["rows"],
                 expo["iters"], expo["train_s"], expo["value"],
                 expo["vs_baseline"]), file=sys.stderr)
        if "level_value" in expo:
            print("# Expo-like LEVEL-PROGRAM growth (num_leaves=2^d, "
                  "max_depth=d): train=%.1fs -> %.2fM row-iters/s, vs "
                  "anchor: %.4f; %.2f device launches/tree "
                  "(level_programs=%d fallback_splits=%d)"
                  % (expo["level_train_s"], expo["level_value"],
                     expo["level_vs_baseline"],
                     expo["level_launches_per_tree"],
                     expo["level_programs"],
                     expo["level_fallback_splits"]), file=sys.stderr)
    allst = None
    if os.environ.get("BENCH_SKIP_ALLSTATE", "") != "1":
        try:
            if bench_telemetry:
                telemetry.reset()
            allst, allst_spread = _repeat_phase(run_allstate, repeats, reset=reset_fn)
            if bench_telemetry:
                phase_snaps["allstate"] = _phase_stats(
                    telemetry, work={"phase": "allstate",
                                     "rows": allst["rows"],
                                     "iters": allst["iters"],
                                     "num_leaves":
                                         allst.get("num_leaves", 255)})
            _copy_spread(spread_out, allst_spread,
                         value="allstate_value",
                         vs_baseline="allstate_vs_baseline")
        except Exception as exc:
            print("# allstate phase failed: %r" % exc, file=sys.stderr)
    if allst is not None:
        result["allstate_value"] = allst["value"]
        result["allstate_vs_baseline"] = allst["vs_baseline"]
        print(json.dumps(result), flush=True)
        print("# Allstate-like sparse one-hot (%d groups for %d features): "
              "rows=%d iters=%d train=%.1fs -> %.2fM row-iters/s, vs anchor"
              " (13.18M*500/348.1s = 18.94M): %.4f"
              % (allst["groups"], allst["features"], allst["rows"],
                 allst["iters"], allst["train_s"], allst["value"],
                 allst["vs_baseline"]), file=sys.stderr)
    yah = None
    if os.environ.get("BENCH_SKIP_YAHOO", "") != "1":
        try:
            if bench_telemetry:
                telemetry.reset()
            yah, yah_spread = _repeat_phase(run_yahoo, repeats, reset=reset_fn)
            if bench_telemetry:
                phase_snaps["yahoo_ltr"] = _phase_stats(
                    telemetry, work={"phase": "yahoo_ltr",
                                     "rows": yah["rows"],
                                     "iters": yah["iters"],
                                     "num_leaves":
                                         yah.get("num_leaves", 255)})
            _copy_spread(spread_out, yah_spread, value="yahoo_value",
                         vs_baseline="yahoo_vs_baseline")
        except Exception as exc:
            print("# yahoo phase failed: %r" % exc, file=sys.stderr)
    if yah is not None:
        result["yahoo_value"] = yah["value"]
        result["yahoo_vs_baseline"] = yah["vs_baseline"]
        print(json.dumps(result), flush=True)
        print("# Yahoo-LTR-like lambdarank: rows=%d iters=%d train=%.1fs "
              "-> %.2fM row-iters/s, vs anchor (473k*500/150.2s = 1.58M): "
              "%.4f" % (yah["rows"], yah["iters"], yah["train_s"],
                        yah["value"], yah["vs_baseline"]), file=sys.stderr)
    vote = None
    if os.environ.get("BENCH_SKIP_VOTING", "") != "1":
        try:
            if bench_telemetry:
                telemetry.reset()
            vote, vote_spread = _repeat_phase(run_voting, repeats, reset=reset_fn)
            if bench_telemetry:
                phase_snaps["voting"] = _phase_stats(
                    telemetry, work={"phase": "voting",
                                     "rows": vote["rows"],
                                     "iters": vote["iters"]})
            _copy_spread(spread_out, vote_spread, value="voting_value",
                         vs_baseline="voting_vs_baseline")
        except Exception as exc:
            print("# voting phase failed: %r" % exc, file=sys.stderr)
    if vote is not None:
        result["voting_value"] = vote["value"]
        result["voting_vs_baseline"] = vote["vs_baseline"]
        for key in ("reduced_feature_frac", "dcn_hist_bytes",
                    "hist_compress_ratio"):
            if key in vote:
                result[key] = vote[key]
        print(json.dumps(result), flush=True)
        print("# voting-parallel (PV-tree persist, %d-device mesh): rows=%d "
              "iters=%d train=%.1fs -> %.2fM row-iters/s (vs the same CPU "
              "anchor: %.4f)" % (vote["devices"], vote["rows"],
                                 vote["iters"], vote["train_s"],
                                 vote["value"], vote["vs_baseline"]),
              file=sys.stderr)
    ckpt = None
    if os.environ.get("BENCH_SKIP_CHECKPOINT", "") != "1":
        try:
            if bench_telemetry:
                telemetry.reset()
            ckpt, ckpt_spread = _repeat_phase(run_checkpoint, repeats,
                                              reset=reset_fn)
            if bench_telemetry:
                phase_snaps["checkpoint"] = _phase_stats(
                    telemetry, work={"phase": "checkpoint",
                                     "rows": ckpt["rows"],
                                     "iters": ckpt["iters"]})
            _copy_spread(spread_out, ckpt_spread,
                         overhead_frac="checkpoint_overhead_frac",
                         write_s="checkpoint_write_s")
        except Exception as exc:
            print("# checkpoint phase failed: %r" % exc, file=sys.stderr)
    if ckpt is not None:
        result["checkpoint_overhead_frac"] = ckpt["overhead_frac"]
        result["checkpoint_write_s"] = ckpt["write_s"]
        result["checkpoint_writes"] = ckpt["writes"]
        result["checkpoint_mb"] = ckpt["mb"]
        print(json.dumps(result), flush=True)
        print("# checkpoint[higgs-like]: rows=%d iters=%d freq=%d -> %d "
              "snapshots (%.1f MB) in %.2fs write time; train %.1fs with "
              "vs %.1fs without = %.2f%% overhead (budget 3%%)"
              % (ckpt["rows"], ckpt["iters"], ckpt["freq"], ckpt["writes"],
                 ckpt["mb"], ckpt["write_s"], ckpt["train_on_s"],
                 ckpt["train_off_s"], 100.0 * ckpt["overhead_frac"]),
              file=sys.stderr)
    pred = None
    if os.environ.get("BENCH_SKIP_PREDICT", "") != "1":
        try:
            if bench_telemetry:
                telemetry.reset()
            # predict returns nested per-shape dicts: repeat by hand and
            # median-merge each sub-dict (spread keys come back dotted)
            runs = []
            for _ in range(max(repeats, 1)):
                if reset_fn is not None:
                    reset_fn()
                runs.append(run_predict())
            if len(runs) == 1:
                pred, pred_spread = runs[0], {}
            else:
                pred, pred_spread = _median_merge_nested(
                    runs, ("higgs", "expo", "poisson"))
            if bench_telemetry:
                phase_snaps["predict"] = _phase_stats(
                    telemetry, work={"phase": "predict",
                                     "rows": pred["higgs"]["rows"]})
            _copy_spread(spread_out, pred_spread, {
                "higgs.value": "predict_value",
                "expo.value": "predict_expo_value",
                "poisson.p50": "predict_p50",
                "poisson.p99": "predict_p99",
                "poisson.qdepth_mean": "predict_qdepth"})
        except Exception as exc:
            print("# predict phase failed: %r" % exc, file=sys.stderr)
    if pred is not None:
        result["predict_value"] = pred["higgs"]["value"]
        result["predict_compiles"] = pred["higgs"]["compiles"]
        result["predict_expo_value"] = pred["expo"]["value"]
        result["predict_expo_compiles"] = pred["expo"]["compiles"]
        slo = pred.get("poisson")
        if slo is not None:
            # serving SLO under the open-loop Poisson load (latency
            # measured from ARRIVAL, so queueing shows up in the tail)
            result["predict_p50"] = slo["p50"]
            result["predict_p99"] = slo["p99"]
            result["predict_qdepth"] = slo["qdepth_mean"]
        print(json.dumps(result), flush=True)
        for shape in ("higgs", "expo"):
            r = pred[shape]
            print("# predict[%s]: %d trees, rows=%d served in %.2fs -> "
                  "%.2fM rows/s, %d serve compiles (bound %d)"
                  % (shape, r["trees"], r["rows"], r["serve_s"], r["value"],
                     r["compiles"], r["compile_bound"]), file=sys.stderr)
        if slo is not None:
            print("# predict[poisson open-loop]: %d requests at %.0f rps "
                  "-> p50=%.1fms p99=%.1fms queue-wait p99=%.1fms, mean "
                  "qdepth %.2f (max %d)"
                  % (slo["requests"], slo["rps"], slo["p50"] * 1e3,
                     slo["p99"] * 1e3, slo["queue_wait_p99"] * 1e3,
                     slo["qdepth_mean"], slo["qdepth_max"]),
                  file=sys.stderr)
    serv = None
    if os.environ.get("BENCH_SKIP_SERVING", "") != "1":
        try:
            if bench_telemetry:
                telemetry.reset()
            serv, serv_spread = _repeat_phase(run_serving, repeats,
                                              reset=reset_fn)
            if bench_telemetry:
                phase_snaps["serving"] = _phase_stats(
                    telemetry, work={"phase": "serving",
                                     "requests": serv["requests"]})
            _copy_spread(spread_out, serv_spread,
                         rps="serving_rps",
                         vs_sync="serving_vs_sync",
                         deadline_miss_frac="serving_deadline_miss_frac")
        except Exception as exc:
            print("# serving phase failed: %r" % exc, file=sys.stderr)
    if serv is not None:
        result["serving_rps"] = serv["rps"]
        result["serving_vs_sync"] = serv["vs_sync"]
        result["serving_deadline_miss_frac"] = serv["deadline_miss_frac"]
        print(json.dumps(result), flush=True)
        print("# serving[async vs sync]: %d reqs x %d clients, %d trees "
              "-> %.0f rps async (%.2fx sync), p50=%.1fms p99=%.1fms, "
              "deadline>%.0fms miss %.1f%%; %d batches (coalesce %.2f "
              "reqs/batch, qdepth max %d)"
              % (serv["requests"], serv["clients"], serv["trees"],
                 serv["rps"], serv["vs_sync"], serv["p50"] * 1e3,
                 serv["p99"] * 1e3, serv["slo_ms"],
                 100.0 * serv["deadline_miss_frac"], serv["batches"],
                 serv["coalesce_ratio"], serv["qdepth_max"]),
              file=sys.stderr)
    swp = None
    if os.environ.get("BENCH_SKIP_SWEEP", "") != "1":
        try:
            if bench_telemetry:
                telemetry.reset()
            swp, swp_spread = _repeat_phase(run_sweep, repeats,
                                            reset=reset_fn)
            if bench_telemetry:
                phase_snaps["sweep"] = _phase_stats(
                    telemetry, work={"phase": "sweep",
                                     "rows": swp["rows"],
                                     "iters": swp["iters"],
                                     "models": swp["models"]})
            _copy_spread(spread_out, swp_spread,
                         models_per_sec="models_per_sec")
        except Exception as exc:
            print("# sweep phase failed: %r" % exc, file=sys.stderr)
    if swp is not None:
        result["models_per_sec"] = swp["models_per_sec"]
        if "sweep_compiles" in swp:
            result["sweep_compiles"] = swp["sweep_compiles"]
        print(json.dumps(result), flush=True)
        print("# sweep[multimodel]: %d models (grid: %s) x %d iters on "
              "rows=%d -> warm %.2fs = %.2f models/s (cold %.2fs%s)"
              % (swp["models"], swp["grid"], swp["iters"], swp["rows"],
                 swp["warm_s"], swp["models_per_sec"], swp["cold_s"],
                 ", %d warm compiles" % swp["sweep_compiles"]
                 if "sweep_compiles" in swp else ""), file=sys.stderr)
    # the self-describing meta block rides the LAST printed json line —
    # the one last-JSON-line parsers archive as `parsed` — so every
    # recorded round is a comparable artifact (schema version, git SHA,
    # device profile, jax version, BENCH_* knobs, repeat count + spread)
    # instead of bare numbers; the perf sentinel keys its lineages and
    # noise bands off this block
    result["meta"] = build_meta(repeats=repeats, spread=spread_out)
    print(json.dumps(result), flush=True)
    # full per-phase telemetry snapshot (category totals + per-scope table)
    # so BENCH_*.json rounds can archive WHERE the time went
    if bench_telemetry:
        phases_out = os.environ.get("BENCH_PHASES_OUT", "BENCH_phases.json")
        try:
            with open(phases_out, "w") as f:
                json.dump(phase_snaps, f, indent=1, sort_keys=True)
            print("# telemetry phase snapshot written to %s" % phases_out,
                  file=sys.stderr)
        except OSError as exc:
            print("# could not write %s: %r" % (phases_out, exc),
                  file=sys.stderr)


# MS-LTR anchor: 2.27M rows x 137 features, lambdarank, 500 iters in
# 215.3 s on the reference box (docs/Experiments.rst:110,143)
LTR_ROWS = 2_270_000
LTR_THROUGHPUT = LTR_ROWS * 500 / 215.3


def run_ltr():
    """MS-LTR-shaped lambdarank throughput (second north-star metric)."""
    import lightgbm_tpu as lgb
    from bench_full import make_ltr_like
    n_iters = int(os.environ.get("BENCH_LTR_ITERS", 160))
    X, y, group = make_ltr_like(
        n_rows=int(os.environ.get("BENCH_LTR_ROWS", LTR_ROWS)))
    n_rows = len(y)
    ds = lgb.Dataset(X, y, group=group)
    ds.construct()
    params = _phase_params({"objective": "lambdarank", "num_leaves": 255,
                            "max_bin": 255, "verbosity": -1,
                            "metric": "none"})
    warm = lgb.train(dict(params), ds, 17, verbose_eval=False)
    warm._booster._materialize_pending()
    del warm
    t0 = time.time()
    booster = lgb.train(dict(params), ds, n_iters, verbose_eval=False)
    booster._booster._materialize_pending()
    import jax
    jax.block_until_ready(booster._booster.train_score.score_device(0))
    train_s = time.time() - t0
    throughput = n_rows * n_iters / train_s
    return {"rows": n_rows, "iters": n_iters, "train_s": train_s,
            "num_leaves": int(params["num_leaves"]),
            "value": round(throughput / 1e6, 3),
            "vs_baseline": round(throughput / LTR_THROUGHPUT, 4)}


def run_expo():
    """Expo-shaped EFB-bundled throughput (one-hot blocks packed into a
    handful of byte groups; persist path with in-kernel bundle decode).

    Two trainings over the same binned dataset:

      * the historical per-split config (num_leaves=255, unbounded
        depth) — keys ``value``/``vs_baseline``, comparable with every
        archived BENCH round;
      * the LEVEL-PROGRAM config (num_leaves=2^d >= the frontier, so
        the no-bind certificate holds at the root and a tree costs
        <= max_depth fused level launches instead of ~num_leaves-1
        split_pass launches — the PR 7 Expo-gap fix) — keys
        ``level_*``, including the counter-measured launches per tree.

    BENCH_EXPO_LEVEL=0 skips the second training; BENCH_EXPO_DEPTH
    picks d (default 8: 256-leaf trees, the 255-leaf class).
    """
    import jax
    import lightgbm_tpu as lgb
    from bench_full import EXPO_SECONDS, make_expo_like
    from lightgbm_tpu.telemetry import events
    n_rows = int(os.environ.get("BENCH_EXPO_ROWS", 2_000_000))
    n_iters = int(os.environ.get("BENCH_EXPO_ITERS", 96))
    X, y = make_expo_like(n_rows)
    ds = lgb.Dataset(X, y)
    ds.construct()
    inner = ds._inner
    anchor = 11_000_000 * 500 / EXPO_SECONDS

    def timed_train(params):
        warm = lgb.train(dict(params), ds, 17, verbose_eval=False)
        warm._booster._materialize_pending()
        del warm
        c0 = events.counts_snapshot()
        t0 = time.time()
        bst = lgb.train(dict(params), ds, n_iters, verbose_eval=False)
        bst._booster._materialize_pending()
        jax.block_until_ready(bst._booster.train_score.score_device(0))
        train_s = time.time() - t0
        c1 = events.counts_snapshot()
        counts = {k: v - c0.get(k, 0) for k, v in c1.items()}
        return bst, train_s, counts

    params = _phase_params({"objective": "binary", "num_leaves": 255,
                            "max_bin": 255, "verbosity": -1,
                            "metric": "none"})
    _, train_s, _ = timed_train(params)
    throughput = n_rows * n_iters / train_s
    out = {"rows": n_rows, "iters": n_iters, "train_s": train_s,
           "groups": len(inner.groups), "features": inner.num_features,
           "num_leaves": int(params["num_leaves"]),
           "value": round(throughput / 1e6, 3),
           "vs_baseline": round(throughput / anchor, 4)}
    if os.environ.get("BENCH_EXPO_LEVEL", "1") != "0":
        d = int(os.environ.get("BENCH_EXPO_DEPTH", 8))
        params_lv = dict(params, num_leaves=1 << d, max_depth=d)
        counting = not events.enabled()   # BENCH_TELEMETRY=0 runs: the
        if counting:                      # launch counters still matter
            events.enable("timers")
        _, lv_s, counts = timed_train(params_lv)
        if counting:
            events.disable()
        lv_tp = n_rows * n_iters / lv_s
        trees = counts.get("tree_learner::persist_scan_trees", 0) \
            or counts.get("tree_learner::v1_grow_trees", 0) or n_iters
        out["level_train_s"] = lv_s
        out["level_value"] = round(lv_tp / 1e6, 3)
        out["level_vs_baseline"] = round(lv_tp / anchor, 4)
        out["level_programs"] = counts.get(
            "tree_learner::level_programs", 0)
        out["level_fallback_splits"] = counts.get(
            "tree_learner::level_fallback_splits", 0)
        out["level_launches_per_tree"] = round(
            (out["level_programs"] + out["level_fallback_splits"])
            / max(trees, 1), 2)
        # fused-iteration pin: compiled-program launches the training
        # loop dispatched per boosting iteration (scan-driver programs +
        # score-delta applies; k-batched gbdt amortizes to ~1/k). LOWER
        # is better — the whole-iteration fusion headline
        out["launches_per_iter"] = round(
            counts.get("tree_learner::iter_launches", 0)
            / max(n_iters, 1), 3)
    return out


# Allstate anchor: 13,184,290 rows x 4228 one-hot columns, 500 iters in
# 348.084s (docs/Experiments.rst) => 18.94M row-iters/s
ALLSTATE_THROUGHPUT = 13_184_290 * 500 / 348.084
# Yahoo LTR anchor: 473,134 rows x 700 features, 500 iters in 150.186s
# (docs/Experiments.rst) => 1.575M row-iters/s
YAHOO_THROUGHPUT = 473_134 * 500 / 150.186


def run_allstate():
    """Allstate-shaped sparse one-hot throughput: ~4.1k binary features
    EFB-bundled into byte groups, ingested as CSR (never densified)."""
    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu.data.synth import make_allstate_like
    n_rows = int(os.environ.get("BENCH_ALLSTATE_ROWS", 1_000_000))
    n_iters = int(os.environ.get("BENCH_ALLSTATE_ITERS", 64))
    X, y = make_allstate_like(n_rows)
    ds = lgb.Dataset(X, y)
    ds.construct()
    inner = ds._inner
    params = _phase_params({"objective": "binary", "num_leaves": 255,
                            "max_bin": 255, "verbosity": -1,
                            "metric": "none"})
    warm = lgb.train(dict(params), ds, 17, verbose_eval=False)
    warm._booster._materialize_pending()
    del warm
    t0 = time.time()
    bst = lgb.train(dict(params), ds, n_iters, verbose_eval=False)
    bst._booster._materialize_pending()
    jax.block_until_ready(bst._booster.train_score.score_device(0))
    train_s = time.time() - t0
    throughput = n_rows * n_iters / train_s
    return {"rows": n_rows, "iters": n_iters, "train_s": train_s,
            "groups": len(inner.groups), "features": inner.num_features,
            "num_leaves": int(params["num_leaves"]),
            "value": round(throughput / 1e6, 3),
            "vs_baseline": round(throughput / ALLSTATE_THROUGHPUT, 4)}


def run_yahoo():
    """Yahoo-LTR-shaped lambdarank throughput (700 dense features)."""
    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu.data.synth import make_yahoo_like
    n_rows = int(os.environ.get("BENCH_YAHOO_ROWS", 473_134))
    n_iters = int(os.environ.get("BENCH_YAHOO_ITERS", 120))
    X, y, group = make_yahoo_like(n_rows)
    ds = lgb.Dataset(X, y, group=group)
    ds.construct()
    params = _phase_params({"objective": "lambdarank", "num_leaves": 255,
                            "max_bin": 255, "verbosity": -1,
                            "metric": "none"})
    warm = lgb.train(dict(params), ds, 17, verbose_eval=False)
    warm._booster._materialize_pending()
    del warm
    t0 = time.time()
    bst = lgb.train(dict(params), ds, n_iters, verbose_eval=False)
    bst._booster._materialize_pending()
    jax.block_until_ready(bst._booster.train_score.score_device(0))
    train_s = time.time() - t0
    n = len(y)
    throughput = n * n_iters / train_s
    return {"rows": n, "iters": n_iters, "train_s": train_s,
            "num_leaves": int(params["num_leaves"]),
            "value": round(throughput / 1e6, 3),
            "vs_baseline": round(throughput / YAHOO_THROUGHPUT, 4)}


def _predict_one_shape(X, y, params, n_trees, serve_rows, tag):
    """Train a model on the shape, then serve `serve_rows` ragged batches
    through the bucketed device runtime; rows/sec + compile count.
    Returns (stats dict, trained booster) — the Poisson SLO phase reuses
    the booster instead of paying a second full training."""
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.predict import BatchServer

    ds = lgb.Dataset(X, y)
    ds.construct()
    bst = lgb.train(dict(params), ds, n_trees, verbose_eval=False)
    bst._booster._materialize_pending()
    server = BatchServer(bst._booster.device_predictor(),
                         min_batch=4096, max_batch=1 << 17)
    rng = np.random.default_rng(0)
    n = len(X)
    # warmup: compile EVERY ladder bucket once so the timed loop measures
    # steady-state serving (the training phases' warmup convention)
    b = server.min_batch
    while b <= server.max_batch:
        server.predict(X[:min(b, n)])
        b <<= 1
    served = 0
    t0 = time.time()
    while served < serve_rows:
        # ragged batch sizes exercise the bucket ladder like real traffic
        k = int(rng.integers(server.min_batch // 2, server.max_batch))
        idx0 = int(rng.integers(0, max(n - k, 1)))
        server.predict(X[idx0:idx0 + min(k, n - idx0)])
        served += min(k, n - idx0)
    serve_s = time.time() - t0
    stats = server.stats()   # per-server: correct with telemetry off AND
    #                        # uncontaminated by the other shape's counters
    return ({"rows": served, "serve_s": serve_s, "trees": bst.num_trees(),
             "value": round(served / serve_s / 1e6, 3),
             "compiles": int(stats["compiles"]),
             "compile_bound": server.max_compiles(), "tag": tag}, bst)


def poisson_open_loop(server, X, rps, n_requests, rng,
                      batch_lo=None, batch_hi=None):
    """Open-loop Poisson load over a warmed BatchServer: SLO percentiles.

    OPEN loop means the arrival schedule is drawn up front (exponential
    inter-arrivals at `rps`) and does NOT slow down when the server
    falls behind — the honest regime for user-facing latency, where a
    stalled server accumulates queue instead of throttling its users
    (the closed-loop rows/sec phases above hide exactly that). Requests
    are served in arrival order on this thread; a request's latency is
    measured from its SCHEDULED ARRIVAL (service start minus arrival is
    its queue wait, recorded by the server), and the queue depth sampled
    at each service start is how many arrived requests were waiting.

    Returns p50/p99 end-to-end seconds, queue-wait p99, and queue-depth
    stats — the BENCH json's predict_p50 / predict_p99 / predict_qdepth.
    """
    import numpy as np
    n = len(X)
    lo = batch_lo if batch_lo is not None else server.min_batch // 2
    hi = batch_hi if batch_hi is not None else server.min_batch * 4
    arrivals = np.cumsum(rng.exponential(1.0 / rps, n_requests))
    sizes = rng.integers(max(lo, 1), max(hi, 2), n_requests)
    starts = rng.integers(0, max(n - int(sizes.max()), 1), n_requests)
    lat = np.empty(n_requests)
    qdepth = np.empty(n_requests, np.int64)
    t0 = time.perf_counter()
    for i in range(n_requests):
        now = time.perf_counter() - t0
        if now < arrivals[i]:
            time.sleep(arrivals[i] - now)
            now = arrivals[i]
        # arrived-but-unstarted requests, this one included
        qdepth[i] = int(np.searchsorted(arrivals, now, side="right")) - i
        k = int(sizes[i])
        i0 = int(starts[i])
        server.predict(X[i0:i0 + min(k, n - i0)],
                       arrival_t=t0 + float(arrivals[i]))
        lat[i] = (time.perf_counter() - t0) - arrivals[i]
    stats = server.stats()
    return {"requests": n_requests, "rps": float(rps),
            "p50": round(float(np.percentile(lat, 50)), 6),
            "p99": round(float(np.percentile(lat, 99)), 6),
            "queue_wait_p99": round(float(stats["queue_wait_p99"]), 6),
            "qdepth_mean": round(float(qdepth.mean()), 3),
            "qdepth_max": int(qdepth.max())}


def run_predict():
    """Inference-subsystem phase: HIGGS-like dense and Expo-like bundled
    shapes served through predict/ (rows/sec + compile counts in the
    BENCH json), plus the open-loop Poisson SLO phase on the HIGGS
    model (predict_p50/p99/qdepth keys)."""
    import numpy as np

    from bench_full import make_expo_like
    from lightgbm_tpu.predict import BatchServer
    n_rows = int(os.environ.get("BENCH_PREDICT_ROWS", 2_000_000))
    n_trees = int(os.environ.get("BENCH_PREDICT_TREES", 100))
    n_leaves = int(os.environ.get("BENCH_PREDICT_LEAVES", 255))
    serve_rows = int(os.environ.get("BENCH_PREDICT_SERVE_ROWS", 8_000_000))
    params = _phase_params({"objective": "binary", "num_leaves": n_leaves,
                            "max_bin": 255, "verbosity": -1,
                            "metric": "none"})
    Xh, yh = make_higgs_like(n_rows)
    higgs, bst_h = _predict_one_shape(Xh, yh, params, n_trees, serve_rows,
                                      "higgs")
    out = {"higgs": higgs}
    if os.environ.get("BENCH_PREDICT_POISSON", "1") != "0":
        # SAME trained model, fresh small-bucket server: SLO traffic is
        # single-user-sized requests, not the throughput phase's 64k-row
        # slabs (the compiled ensemble tensors are cached on the
        # booster; only the small ladder buckets compile here)
        server = BatchServer(bst_h._booster.device_predictor(),
                             min_batch=256, max_batch=4096)
        b = server.min_batch
        while b <= server.max_batch:     # warm every ladder bucket
            server.predict(Xh[:b])
            b <<= 1
        rng = np.random.default_rng(7)
        out["poisson"] = poisson_open_loop(
            server, Xh,
            rps=float(os.environ.get("BENCH_PREDICT_RPS", 50.0)),
            n_requests=int(os.environ.get("BENCH_PREDICT_POISSON_REQS",
                                          400)),
            rng=rng)
    del Xh, yh, bst_h
    Xe, ye = make_expo_like(min(n_rows, 1_000_000))
    out["expo"] = _predict_one_shape(Xe, ye, params, n_trees,
                                     serve_rows // 2, "expo")[0]
    return out


def run_serving():
    """Serving-subsystem phase: the IDENTICAL request mix (sizes, row
    offsets, client concurrency) driven through the synchronous
    BatchServer and the continuous-batching AsyncBatchServer sharing one
    compiled predictor (so the jit ladder is warm for both and the delta
    is pure serving architecture). Clients are a thread pool — the sync
    server serializes a device round-trip per request, the async server
    coalesces concurrent sub-bucket requests into shared batches.

    BENCH keys: serving_rps (sustained async requests/s), serving_vs_sync
    (async speedup over sync on the same mix; acceptance floor 2x on a
    coalescable mix), serving_deadline_miss_frac (fraction of async
    requests over BENCH_SERVING_SLO_MS end-to-end)."""
    import numpy as np
    from concurrent.futures import ThreadPoolExecutor

    import lightgbm_tpu as lgb
    from lightgbm_tpu.predict import BatchServer
    from lightgbm_tpu.serving import AsyncBatchServer

    n_rows = int(os.environ.get("BENCH_SERVING_ROWS", 500_000))
    n_trees = int(os.environ.get("BENCH_SERVING_TREES", 100))
    n_leaves = int(os.environ.get("BENCH_SERVING_LEAVES", 255))
    n_requests = int(os.environ.get("BENCH_SERVING_REQS", 400))
    n_clients = int(os.environ.get("BENCH_SERVING_CLIENTS", 8))
    slo_ms = float(os.environ.get("BENCH_SERVING_SLO_MS", 50.0))
    max_wait_ms = float(os.environ.get("BENCH_SERVING_MAX_WAIT_MS", 5.0))
    # single-user-sized requests: each pads to the 256-row min bucket on
    # the sync path, so coalescing them is where continuous batching
    # earns its keep (a 256-row mix would measure pure dispatch overlap)
    req_lo = int(os.environ.get("BENCH_SERVING_REQ_LO", 1))
    req_hi = int(os.environ.get("BENCH_SERVING_REQ_HI", 64))
    params = _phase_params({"objective": "binary", "num_leaves": n_leaves,
                            "max_bin": 255, "verbosity": -1,
                            "metric": "none"})
    X, y = make_higgs_like(n_rows)
    ds = lgb.Dataset(X, y)
    ds.construct()
    bst = lgb.train(dict(params), ds, n_trees, verbose_eval=False)
    pred = bst._booster.device_predictor()
    # the request mix: single-user-sized slices, drawn ONCE and replayed
    # verbatim through both servers
    rng = np.random.default_rng(11)
    sizes = rng.integers(req_lo, req_hi + 1, n_requests)
    starts = rng.integers(0, max(n_rows - req_hi - 1, 1), n_requests)
    reqs = [X[int(starts[i]):int(starts[i]) + int(sizes[i])]
            for i in range(n_requests)]

    def drive(predict_fn):
        lat = np.empty(n_requests)

        def one(i):
            t0 = time.perf_counter()
            predict_fn(reqs[i])
            lat[i] = time.perf_counter() - t0

        t0 = time.time()
        with ThreadPoolExecutor(n_clients) as pool:
            list(pool.map(one, range(n_requests)))
        return time.time() - t0, lat

    sync = BatchServer(pred, min_batch=256, max_batch=4096)
    b = sync.min_batch
    while b <= sync.max_batch:        # warm the shared ladder once
        sync.predict(X[:b])
        b <<= 1
    t_sync, _lat_sync = drive(sync.predict)
    with AsyncBatchServer(pred, min_batch=256, max_batch=4096,
                          max_wait_ms=max_wait_ms) as server:
        t_async, lat_async = drive(server.predict)
        stats = server.stats()
    return {
        "rows": n_rows, "trees": bst.num_trees(),
        "requests": n_requests, "clients": n_clients,
        "slo_ms": slo_ms, "max_wait_ms": max_wait_ms,
        "sync_s": round(t_sync, 4), "async_s": round(t_async, 4),
        "rps": round(n_requests / t_async, 2),
        "vs_sync": round(t_sync / t_async, 3),
        "deadline_miss_frac": round(
            float((lat_async > slo_ms / 1e3).mean()), 4),
        "p50": round(float(np.percentile(lat_async, 50)), 6),
        "p99": round(float(np.percentile(lat_async, 99)), 6),
        "batches": int(stats["batches"]),
        "coalesce_ratio": float(stats["coalesce_ratio"]),
        "qdepth_max": int(stats["qdepth_max"]),
    }


def run_sweep():
    """Multi-model sweep phase (multimodel/): B boosters trained over ONE
    shared binned Dataset through the model-axis vmap of the fused
    iteration, per-model knobs riding as traced [B] inputs.

    BENCH keys: models_per_sec (B over the post-warm sweep wall — the
    number the model-axis batching exists to scale) and sweep_compiles
    (tree_learner::mm_programs counter delta around the WARM sweep; the
    power-of-two bucket ladder exists so this is 0 — telemetry-on rounds
    only). BENCH_SWEEP_MODELS sets B; BENCH_SWEEP_GRID names the swept
    knob(s) (comma list from the traced set, so every grid stays ONE
    static group / one program chain regardless of B)."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu import multimodel
    from lightgbm_tpu.telemetry import events as tel_events

    # defaults sized to the recorded CPU-lineage rounds (the other
    # phases' 20k x 20 scale); TPU rounds crank the knobs — they enter
    # the lineage fingerprint, defaults do not
    n_rows = int(os.environ.get("BENCH_SWEEP_ROWS", 20_000))
    n_iters = int(os.environ.get("BENCH_SWEEP_ITERS", 20))
    n_models = int(os.environ.get("BENCH_SWEEP_MODELS", 4))
    grid_keys = [s.strip() for s in os.environ.get(
        "BENCH_SWEEP_GRID", "learning_rate").split(",") if s.strip()]
    X, y = make_higgs_like(n_rows)
    ds = lgb.Dataset(X, y)
    ds.construct()
    base = _phase_params({"objective": "binary", "num_leaves": 63,
                          "max_bin": 255, "verbosity": -1,
                          "metric": "none"})
    # the driver batches the fused-scan program family; persist-eligible
    # members fall back to their own serial loop (batching the persist
    # family is future work), so a BENCH_PARAMS tpu_persist_scan=force
    # would silently measure B serial loops here. Pin the batched path.
    base["tpu_persist_scan"] = "off"
    # spans for the per-model (traced) knobs; anything else would split
    # the grid into several static groups and measure chaining, not
    # batching
    spans = {"learning_rate": (0.05, 0.2), "lambda_l1": (0.0, 1.0),
             "lambda_l2": (0.0, 2.0), "min_gain_to_split": (0.0, 0.1),
             "min_data_in_leaf": (20, 80)}
    grid = []
    for i in range(n_models):
        p = dict(base)
        for key in grid_keys:
            lo, hi = spans.get(key, (0.05, 0.2))
            v = lo + (hi - lo) * i / max(n_models - 1, 1)
            p[key] = (int(round(v)) if key == "min_data_in_leaf"
                      else round(v, 6))
        grid.append(p)

    def one_sweep():
        # sweep materializes every model's trees before returning, so the
        # wall includes the full async pipeline drain
        t0 = time.time()
        multimodel.sweep(grid, ds, num_boost_round=n_iters)
        return time.time() - t0

    cold_s = one_sweep()          # compiles the bucket-ladder programs
    c0 = tel_events.counts_snapshot().get("tree_learner::mm_programs", 0.0)
    warm_s = one_sweep()
    c1 = tel_events.counts_snapshot().get("tree_learner::mm_programs", 0.0)
    out = {"rows": n_rows, "iters": n_iters, "models": n_models,
           "grid": ",".join(grid_keys),
           "cold_s": round(cold_s, 3), "warm_s": round(warm_s, 3),
           "models_per_sec": round(n_models / warm_s, 4)}
    if tel_events.enabled():
        out["sweep_compiles"] = int(c1 - c0)
    return out


def run_checkpoint():
    """Resilience-subsystem phase: HIGGS-like training with
    snapshot_freq=10 full-state checkpoints vs the same run with them off.
    Reports the wall overhead fraction (acceptance budget: < 3%) plus the
    write time / count / bytes from the checkpoint::* telemetry."""
    import shutil
    import tempfile

    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu import telemetry

    n_rows = int(os.environ.get("BENCH_CHECKPOINT_ROWS", 2_000_000))
    n_iters = int(os.environ.get("BENCH_CHECKPOINT_ITERS", 60))
    freq = int(os.environ.get("BENCH_CHECKPOINT_FREQ", 10))
    n_leaves = int(os.environ.get("BENCH_CHECKPOINT_LEAVES", 255))
    X, y = make_higgs_like(n_rows)
    ds = lgb.Dataset(X, y)
    ds.construct()
    base = _phase_params({"objective": "binary", "num_leaves": n_leaves,
                          "max_bin": 255, "verbosity": -1,
                          "metric": "none"})

    def _timed_train(params, wipe_dir=None):
        warm = lgb.train(dict(params), ds, 17, verbose_eval=False)
        warm._booster._materialize_pending()
        del warm
        if wipe_dir is not None:
            # the warmup wrote snapshots; the timed run must train the
            # full n_iters (not resume from them) and the checkpoint::*
            # telemetry must count only the timed run's writes
            for name in os.listdir(wipe_dir):
                os.remove(os.path.join(wipe_dir, name))
            telemetry.reset()
        t0 = time.time()
        bst = lgb.train(dict(params), ds, n_iters, verbose_eval=False)
        bst._booster._materialize_pending()
        jax.block_until_ready(bst._booster.train_score.score_device(0))
        return time.time() - t0

    t_off = _timed_train(base)
    ckpt_dir = tempfile.mkdtemp(prefix="bench_ckpt_")
    try:
        on = dict(base)
        on.update({"snapshot_freq": freq, "checkpoint_dir": ckpt_dir,
                   "checkpoint_keep": 2})
        t_on = _timed_train(on, wipe_dir=ckpt_dir)
        if t_on - t_off > 0.03 * t_off:
            # A shared-CPU steal burst landing in one arm of the A/B
            # masquerades as snapshot overhead (the writes themselves
            # are milliseconds — see write_s). Re-measure each arm once
            # and keep the per-arm minimum: the burst-rejecting
            # estimator, paid only when the first pair blew the budget.
            t_off = min(t_off, _timed_train(base))
            t_on = min(t_on, _timed_train(on, wipe_dir=ckpt_dir))
        counts = telemetry.events.counts_snapshot()
        scopes = telemetry.events.snapshot_full()
        write_s = scopes.get("checkpoint::write", (0.0, 0, ""))[0]
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)
    return {"rows": n_rows, "iters": n_iters, "freq": freq,
            "train_on_s": t_on, "train_off_s": t_off,
            "overhead_frac": round(max(t_on - t_off, 0.0)
                                   / max(t_off, 1e-9), 4),
            "write_s": round(float(write_s), 3),
            "writes": int(counts.get("checkpoint::write", 0)),
            "mb": round(counts.get("checkpoint::bytes", 0) / 1e6, 2)}


def run_voting():
    """Voting-parallel throughput on the available mesh (PV-tree on the
    sharded persist driver). On a 1-chip box the mesh is degenerate but the
    full voting program (local scan, vote psum, selective reduce) runs —
    the line tracks its overhead vs the plain persist path."""
    import jax
    import lightgbm_tpu as lgb
    n_rows = int(os.environ.get("BENCH_VOTING_ROWS", 4_000_000))
    n_iters = int(os.environ.get("BENCH_VOTING_ITERS", 120))
    X, y = make_higgs_like(n_rows)
    ds = lgb.Dataset(X, y)
    ds.construct()
    params = _phase_params({"objective": "binary", "num_leaves": 255,
                            "max_bin": 255, "verbosity": -1,
                            "metric": "none", "tree_learner": "voting",
                            "top_k": 14})
    warm = lgb.train(dict(params), ds, 17, verbose_eval=False)
    warm._booster._materialize_pending()
    del warm
    t0 = time.time()
    bst = lgb.train(dict(params), ds, n_iters, verbose_eval=False)
    bst._booster._materialize_pending()
    jax.block_until_ready(bst._booster.train_score.score_device(0))
    train_s = time.time() - t0
    throughput = n_rows * n_iters / train_s
    out = {"rows": n_rows, "iters": n_iters, "train_s": train_s,
           "devices": len(jax.devices()),
           "value": round(throughput / 1e6, 3),
           "vs_baseline": round(throughput / REF_THROUGHPUT, 4)}
    # communication-efficiency keys (ROADMAP item 2): the PV-Tree
    # pre-selection ratio and the flush-time wire-byte model — present
    # when the persist path ran with telemetry on; BENCH_PARAMS=
    # "tpu_hist_quant=int16" records a quantized round (its own
    # comparability lineage via meta.knobs)
    tl = bst._booster.tree_learner
    gr = getattr(tl, "_persist_gr", None)
    if gr is not None:
        tl.flush_level_stats()
        out["reduced_feature_frac"] = round(
            float(getattr(gr, "reduced_feature_frac", 1.0)), 4)
        from lightgbm_tpu.telemetry import events as tel_events
        counts = tel_events.counts_snapshot()
        dcn = counts.get("collective::dcn_hist_bytes", 0)
        fullb = counts.get("collective::dcn_hist_bytes_fullwidth", 0)
        if dcn:
            out["dcn_hist_bytes"] = int(dcn)
        if dcn and fullb:
            out["hist_compress_ratio"] = round(fullb / dcn, 3)
    return out


if __name__ == "__main__":
    main()
