"""Quick perf sweep of grower knobs on the real chip (dev tool, not CI)."""
import sys
import time

import numpy as np

from bench import make_higgs_like

import lightgbm_tpu as lgb


def run(n_rows, n_iters, leaves, wc, hd, ds_cache={}):
    if n_rows not in ds_cache:
        X, y = make_higgs_like(n_rows)
        t0 = time.time()
        ds = lgb.Dataset(X, y)
        ds.construct()
        print(f"# binning {n_rows} rows: {time.time()-t0:.1f}s", flush=True)
        ds_cache[n_rows] = ds
    ds = ds_cache[n_rows]
    params = {"objective": "binary", "num_leaves": leaves, "max_bin": 255,
              "verbosity": -1, "metric": "none",
              "tpu_window_chunk": wc, "tpu_hist_dtype": hd}
    t0 = time.time()
    # 17 = one fused 16-iteration scan + one single-tree program: compiles
    # BOTH steady-state paths so the measured run is compile-free
    warm = lgb.train(dict(params), ds, 17, verbose_eval=False)
    warm._booster._materialize_pending()
    compile_s = time.time() - t0
    del warm
    t0 = time.time()
    bst = lgb.train(dict(params), ds, n_iters, verbose_eval=False)
    bst._booster._materialize_pending()
    import jax
    jax.block_until_ready(bst._booster.train_score.score_device(0))
    train_s = time.time() - t0
    thr = n_rows * n_iters / train_s / 1e6
    print(f"rows={n_rows:8d} iters={n_iters} leaves={leaves:3d} wc={wc:6d} "
          f"hist={hd:6s} compile={compile_s:5.1f}s train={train_s:6.1f}s "
          f"({train_s/n_iters*1000:7.1f} ms/tree) {thr:7.3f} Mri/s", flush=True)


CONFIGS = [
    # decompose: fixed-per-split vs row-cost
    (1_000_000, 15, 255, 2048, "f32"),
    (1_000_000, 15, 63, 2048, "f32"),
    (250_000, 15, 255, 2048, "f32"),
    (1_000_000, 15, 255, 1024, "f32"),
    (1_000_000, 15, 255, 2048, "bf16x2"),
    (1_000_000, 15, 255, 4096, "f32"),
]
if len(sys.argv) > 1:
    CONFIGS = [tuple(int(x) if x.isdigit() else x for x in a.split(","))
               for a in sys.argv[1:]]

for cfg in CONFIGS:
    run(*cfg)
