"""Microbenchmark the per-split components of the partitioned grower on the
real chip (dev tool, not CI): pack (sort vs matmul) at several chunk sizes,
the Pallas histogram chunk, and the dense best-split scan. Thin wrapper —
the benchmarks live in lightgbm_tpu.telemetry.hostprof."""
from lightgbm_tpu.telemetry.hostprof import run_split_microbench

if __name__ == "__main__":
    run_split_microbench()
