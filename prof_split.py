"""Microbenchmark the per-split components of the partitioned grower on the
real chip (dev tool, not CI): pack (sort vs matmul) at several chunk sizes,
the Pallas histogram chunk, and the dense best-split scan. Identifies where
the ~ms/split fixed cost lives."""
import time

import numpy as np

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb  # noqa: F401  (x64 etc.)
from lightgbm_tpu.ops import grow as G
from lightgbm_tpu.ops.split import SplitParams, find_best_split_numerical


def timeit(fn, *args, iters=50):
    fn(*args)  # compile
    jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def bench_pack(C, G_=28):
    rng = np.random.default_rng(0)
    bw = jnp.asarray(rng.integers(0, 255, (C, G_)), jnp.uint8)
    gw = jnp.asarray(rng.normal(size=C), jnp.float32)
    hw = jnp.asarray(rng.random(C), jnp.float32)
    rbw = jnp.asarray(rng.integers(0, 1 << 30, C), jnp.uint32)
    key = jnp.asarray(rng.integers(0, 3, C), jnp.uint32)

    @jax.jit
    def sort_pack(key, bw, gw, hw, rbw):
        return G._pack_sort(key, bw, gw, hw, rbw, 8)

    t_sort = timeit(sort_pack, key, bw, gw, hw, rbw)

    gl = key == 0
    gr = key == 2

    @jax.jit
    def mm_pack(gl, gr, bw, gw, hw, rbw):
        posl = jnp.cumsum(gl, dtype=jnp.int32) - 1
        nR = jnp.sum(gr, dtype=jnp.int32)
        posr = (C - nR) + jnp.cumsum(gr, dtype=jnp.int32) - 1
        slot = jnp.where(gl, posl, jnp.where(gr, posr, C))
        rb_hi = (rbw >> jnp.uint32(12)).astype(jnp.float32)
        rb_lo = (rbw & jnp.uint32(4095)).astype(jnp.float32)
        payload = jnp.concatenate([
            bw.astype(jnp.float32), gw[:, None], hw[:, None],
            rb_hi[:, None], rb_lo[:, None]], axis=1)
        return G._pack_matmul(slot, payload, C)

    t_mm = timeit(mm_pack, gl, gr, bw, gw, hw, rbw)
    print(f"pack C={C:6d}: sort={t_sort*1e6:8.1f}us "
          f"({t_sort/C*1e9:6.2f} ns/row)  matmul={t_mm*1e6:8.1f}us "
          f"({t_mm/C*1e9:6.2f} ns/row)")


def bench_hist_chunk(C, G_=28, W=256):
    rng = np.random.default_rng(0)
    bw = jnp.asarray(rng.integers(0, 255, (C, G_)), jnp.int32)
    gw = jnp.asarray(rng.normal(size=C), jnp.float32)
    hw = jnp.asarray(rng.random(C), jnp.float32)
    from lightgbm_tpu.ops.pallas_histogram import hist_window

    @jax.jit
    def pallas_chunk(bw, gw, hw):
        return hist_window(bw.T, gw, hw, W)

    t = timeit(pallas_chunk, bw, gw, hw)
    print(f"hist C={C:6d}: pallas={t*1e6:8.1f}us ({t/C*1e9:6.2f} ns/row)")


def bench_scan(F=28, W=256):
    TB = F * (W - 1)
    rng = np.random.default_rng(0)
    hist = jnp.asarray(rng.random((TB, 2)), jnp.float32)
    from lightgbm_tpu.ops.split import FeatureMeta
    bs = jnp.arange(F, dtype=jnp.int32) * (W - 1)
    meta = FeatureMeta(
        feat_id=jnp.repeat(jnp.arange(F, dtype=jnp.int32), W - 1),
        bin_start=bs, bin_end=bs + (W - 1),
        missing_type=jnp.zeros(F, jnp.int32),
        default_bin=jnp.zeros(F, jnp.int32),
        monotone=jnp.zeros(F, jnp.int32),
        is_categorical=jnp.zeros(F, bool),
        penalty=jnp.ones(F, jnp.float64))
    params = SplitParams.from_config(lgb.Config({}))
    fmask = jnp.ones(F, bool)

    @jax.jit
    def scan2(hist2):
        def one(h):
            return find_best_split_numerical(
                h, jnp.asarray(1.0, jnp.float32), jnp.asarray(100.0, jnp.float32),
                jnp.asarray(1000, jnp.int32), meta, params,
                jnp.asarray(-jnp.inf, jnp.float32),
                jnp.asarray(jnp.inf, jnp.float32), fmask,
                num_features=F, use_mc=False, max_w=W, use_dp=False,
                use_l1=False, use_mds=False)
        return jax.vmap(one)(hist2)

    hist2 = jnp.stack([hist, hist])
    t = timeit(scan2, hist2)
    print(f"scan pair (F={F}, W={W}): {t*1e6:8.1f}us")


def bench_full_split_body(n_l, C):
    """End-to-end cost proxy: pass A + pass B chunk loops for one split of a
    leaf with n_l rows."""
    print("(full-body benchmarks live in sweep_perf.py tree timing)")


if __name__ == "__main__":
    for C in (1024, 2048, 4096, 8192, 16384):
        bench_pack(C)
    for C in (2048, 8192, 32768):
        bench_hist_chunk(C)
    bench_scan()
