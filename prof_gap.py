"""Where does wall time go between fused-scan dispatches? (dev tool)"""
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from bench import make_higgs_like

rows = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
X, y = make_higgs_like(rows)
ds = lgb.Dataset(X, y)
ds.construct()
params = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
          "verbosity": -1, "metric": "none"}
warm = lgb.train(dict(params), ds, 17, verbose_eval=False)
warm._booster._materialize_pending()
del warm

booster = lgb.Booster(params=dict(params), train_set=ds)
b = booster._booster
b.planned_rounds = 32
b.allow_batch = True
t0 = time.perf_counter()
b.train_one_iter(None, None)  # batch 1 dispatch
t1 = time.perf_counter()
for _ in range(15):
    b.train_one_iter(None, None)  # credit burn
t2 = time.perf_counter()
b.train_one_iter(None, None)  # batch 2 dispatch
t3 = time.perf_counter()
for _ in range(15):
    b.train_one_iter(None, None)
t4 = time.perf_counter()
sc = b.train_score.score_device(0)
jax.block_until_ready(sc)
t5 = time.perf_counter()
b._materialize_pending()
t6 = time.perf_counter()
print(f"batch1 dispatch: {t1-t0:.3f}s")
print(f"credit iters:    {t2-t1:.3f}s")
print(f"batch2 dispatch: {t3-t2:.3f}s")
print(f"credit iters:    {t4-t3:.3f}s")
print(f"block on score:  {t5-t4:.3f}s")
print(f"materialize:     {t6-t5:.3f}s")
print(f"total 32 iters:  {t6-t0:.3f}s -> {rows*32/(t6-t0)/1e6:.2f} Mri/s")
