"""Thin setup.py shim — metadata lives in pyproject.toml.

Kept for editable installs on older pips and so the native C++ sources
(lightgbm_tpu/native/*.cpp, compiled lazily at first use with the system
g++ — see lightgbm_tpu/native/__init__.py) ship inside wheels/sdists.
"""
from setuptools import setup

setup()
