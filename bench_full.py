"""Full-scale end-to-end benchmark at the reference's experiment configs.

Mirrors docs/Experiments.rst:76-147 (HIGGS 10.5M x 28, 500 iters, 255
leaves) and the MS-LTR lambdarank shape (2.27M x 137, NDCG@10,
docs/Experiments.rst:110,143). The real datasets need downloads (zero
egress here), so both use synthetic stand-ins of the same shape; absolute
accuracy therefore has its own scale, and the meaningful accuracy gate is
PARITY: the TPU fast path must reach the same train metric as this
framework's reference-faithful f64 path at equal config (checked at a
reduced size where the f64 path is affordable).

Prints one JSON line per experiment plus a combined summary line.
Wall-clock anchors (BASELINE.md): HIGGS 238.5 s, MS-LTR 215.3 s
(500 iterations, 2x E5-2670v3, 16 threads).
"""
import json
import os
import sys
import time

import numpy as np

from bench import build_meta, make_higgs_like
from lightgbm_tpu.data.synth import (make_allstate_like,  # noqa: F401
                                     make_expo_like, make_ltr_like,
                                     make_yahoo_like)

HIGGS_SECONDS = 238.5
MSLTR_SECONDS = 215.3
# Allstate: 13,184,290 rows x 4228 (mostly one-hot) columns, 500 iters in
# 348.084s; Yahoo LTR: 473,134 rows x 700 features, 500 iters in 150.186s
# (docs/Experiments.rst comparison table — the two reference experiments
# VERDICT round 5 flagged as never benched)
ALLSTATE_SECONDS = 348.084
ALLSTATE_ROWS_REF = 13_184_290
YAHOO_SECONDS = 150.186
YAHOO_ROWS_REF = 473_134


def auc(y, p):
    order = np.argsort(p, kind="mergesort")
    y = np.asarray(y)[order]
    n_pos = y.sum()
    n_neg = len(y) - n_pos
    ranks = np.arange(1, len(y) + 1)
    return (ranks[y > 0].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def run_higgs(n_rows, n_iters):
    import lightgbm_tpu as lgb
    X, y = make_higgs_like(n_rows)
    t0 = time.time()
    ds = lgb.Dataset(X, y)
    ds.construct()
    t_bin = time.time() - t0
    params = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
              "verbosity": -1, "metric": "none"}
    warm = lgb.train(dict(params), ds, 17, verbose_eval=False)
    warm._booster._materialize_pending()
    del warm
    t0 = time.time()
    bst = lgb.train(dict(params), ds, n_iters, verbose_eval=False)
    bst._booster._materialize_pending()
    t_train = time.time() - t0
    # train AUC from the device scores (raw score sigmoid-monotone)
    bst._booster._sync_persist_scores()
    import jax
    raw = np.asarray(bst._booster.train_score.score_device(0))
    a = auc(y, raw)
    return {"experiment": "higgs_like", "rows": n_rows, "iters": n_iters,
            "binning_s": round(t_bin, 1), "train_s": round(t_train, 1),
            "train_auc": round(float(a), 6),
            "ref_train_s": HIGGS_SECONDS,
            "speedup_vs_ref_cpu": round(
                HIGGS_SECONDS / t_train * (n_iters / 500), 3)}


# make_ltr_like now lives in lightgbm_tpu.data.synth (imported above) so
# the profiling CLI and tests share the generator.


def ndcg_at_k(labels, scores, group, k=10):
    out = []
    off = 0
    for g in group:
        lab = labels[off:off + g]
        sc = scores[off:off + g]
        off += g
        order = np.argsort(-sc, kind="mergesort")[:k]
        gains = (2.0 ** lab[order] - 1) / np.log2(np.arange(2, len(order) + 2))
        ideal = np.sort(lab)[::-1][:k]
        ig = (2.0 ** ideal - 1) / np.log2(np.arange(2, len(ideal) + 2))
        denom = ig.sum()
        if denom > 0:
            out.append(gains.sum() / denom)
    return float(np.mean(out))


def run_ltr(n_rows, n_iters):
    import lightgbm_tpu as lgb
    X, y, group = make_ltr_like(n_rows)
    t0 = time.time()
    ds = lgb.Dataset(X, y, group=group)
    ds.construct()
    t_bin = time.time() - t0
    params = {"objective": "lambdarank", "num_leaves": 255, "max_bin": 255,
              "verbosity": -1, "metric": "none",
              "lambdarank_truncation_level": 30}
    t0 = time.time()
    bst = lgb.train(dict(params), ds, n_iters, verbose_eval=False)
    bst._booster._materialize_pending()
    t_train = time.time() - t0
    bst._booster._sync_persist_scores()
    raw = np.asarray(bst._booster.train_score.score_device(0))
    nd = ndcg_at_k(y, raw, group, 10)
    return {"experiment": "msltr_like", "rows": len(y), "iters": n_iters,
            "binning_s": round(t_bin, 1), "train_s": round(t_train, 1),
            "train_ndcg10": round(nd, 6),
            "ref_train_s": MSLTR_SECONDS,
            "speedup_vs_ref_cpu": round(
                MSLTR_SECONDS / t_train * (n_iters / 500), 3)}


def run_parity(n_rows=300_000, n_iters=48):
    """TPU fast path vs the reference-faithful path at equal config."""
    import lightgbm_tpu as lgb
    X, y = make_higgs_like(n_rows)
    out = {}
    for mode in ("auto", "off"):
        params = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
                  "verbosity": -1, "metric": "none",
                  "tpu_persist_scan": mode}
        ds = lgb.Dataset(X, y)
        bst = lgb.train(dict(params), ds, n_iters, verbose_eval=False)
        out[mode] = auc(y, bst.predict(X, raw_score=True))
    return {"experiment": "path_parity", "rows": n_rows, "iters": n_iters,
            "auc_fast_path": round(float(out["auto"]), 6),
            "auc_reference_path": round(float(out["off"]), 6),
            "auc_delta": round(float(abs(out["auto"] - out["off"])), 6)}


def main():
    rows = int(os.environ.get("BENCHF_ROWS", 10_500_000))
    iters = int(os.environ.get("BENCHF_ITERS", 500))
    ltr_rows = int(os.environ.get("BENCHF_LTR_ROWS", 2_270_000))
    ltr_iters = int(os.environ.get("BENCHF_LTR_ITERS", 100))
    results = []
    results.append(run_parity())
    print(json.dumps(results[-1]), flush=True)
    results.append(run_higgs(rows, iters))
    print(json.dumps(results[-1]), flush=True)
    results.append(run_ltr(ltr_rows, ltr_iters))
    print(json.dumps(results[-1]), flush=True)
    if os.environ.get("BENCHF_SKIP_ALLSTATE", "") != "1":
        results.append(run_allstate(
            int(os.environ.get("BENCHF_ALLSTATE_ROWS", 4_000_000)),
            int(os.environ.get("BENCHF_ALLSTATE_ITERS", 100))))
        print(json.dumps(results[-1]), flush=True)
    if os.environ.get("BENCHF_SKIP_YAHOO", "") != "1":
        results.append(run_yahoo(
            int(os.environ.get("BENCHF_YAHOO_ROWS", 473_134)),
            int(os.environ.get("BENCHF_YAHOO_ITERS", 200))))
        print(json.dumps(results[-1]), flush=True)
    if os.environ.get("BENCHF_SKIP_EXPO", "") != "1":
        results.append(run_expo_level(
            int(os.environ.get("BENCHF_EXPO_ROWS", 2_000_000)),
            int(os.environ.get("BENCHF_EXPO_ITERS", 96))))
        print(json.dumps(results[-1]), flush=True)
    # the same self-describing meta block bench.py stamps: a bench_full
    # line is a comparable artifact too (BENCHF_* knobs ride along via
    # the BENCH prefix match)
    print(json.dumps({"metric": "bench_full", "results": results,
                      "meta": build_meta()}))


# Expo anchor: 11M rows x ~700 one-hot features, 500 iters in 138.5s
# (docs/Experiments.rst:112) => 39.7M row-iters/s
EXPO_SECONDS = 138.5


def run_expo_level(n_rows, n_iters):
    """Expo-shaped EFB-bundled training through the LEVEL-PROGRAM grower
    (PR 7): num_leaves = 2^d with max_depth = d so the no-bind
    certificate holds at the root and a tree costs <= d fused level
    launches instead of ~num_leaves-1 per-split ones. Reports the
    ``expo_level_*`` keys BENCH rounds compare before/after on."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.telemetry import events
    d = int(os.environ.get("BENCHF_EXPO_DEPTH", 8))
    X, y = make_expo_like(n_rows)
    t0 = time.time()
    ds = lgb.Dataset(X, y)
    ds.construct()
    t_bin = time.time() - t0
    params = {"objective": "binary", "num_leaves": 1 << d, "max_depth": d,
              "max_bin": 255, "verbosity": -1, "metric": "none"}
    warm = lgb.train(dict(params), ds, 17, verbose_eval=False)
    warm._booster._materialize_pending()
    del warm
    counting = not events.enabled()
    if counting:
        events.enable("timers")
    c0 = events.counts_snapshot()
    t0 = time.time()
    bst = lgb.train(dict(params), ds, n_iters, verbose_eval=False)
    bst._booster._materialize_pending()
    t_train = time.time() - t0
    c1 = events.counts_snapshot()
    if counting:
        events.disable()
    counts = {k: v - c0.get(k, 0) for k, v in c1.items()}
    bst._booster._sync_persist_scores()
    raw = np.asarray(bst._booster.train_score.score_device(0))
    a = auc(y, raw)
    trees = counts.get("tree_learner::persist_scan_trees", 0) \
        or counts.get("tree_learner::v1_grow_trees", 0) or n_iters
    lv = counts.get("tree_learner::level_programs", 0)
    fb = counts.get("tree_learner::level_fallback_splits", 0)
    return {"experiment": "expo_level", "rows": n_rows, "iters": n_iters,
            "depth": d, "binning_s": round(t_bin, 1),
            "train_s": round(t_train, 1), "train_auc": round(float(a), 6),
            "expo_level_programs": lv, "expo_level_fallback_splits": fb,
            "expo_level_launches_per_tree": round(
                (lv + fb) / max(trees, 1), 2),
            "ref_train_s": EXPO_SECONDS,
            "speedup_vs_ref_cpu": round(
                EXPO_SECONDS / max(t_train, 1e-9) * (n_iters / 500)
                * (n_rows / 11_000_000), 3)}


def run_allstate(n_rows, n_iters):
    """Allstate-shaped sparse one-hot training (wide EFB bundling)."""
    import lightgbm_tpu as lgb
    X, y = make_allstate_like(n_rows)
    t0 = time.time()
    ds = lgb.Dataset(X, y)
    ds.construct()
    t_bin = time.time() - t0
    params = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
              "verbosity": -1, "metric": "none"}
    warm = lgb.train(dict(params), ds, 17, verbose_eval=False)
    warm._booster._materialize_pending()
    del warm
    t0 = time.time()
    bst = lgb.train(dict(params), ds, n_iters, verbose_eval=False)
    bst._booster._materialize_pending()
    t_train = time.time() - t0
    bst._booster._sync_persist_scores()
    raw = np.asarray(bst._booster.train_score.score_device(0))
    a = auc(y, raw)
    return {"experiment": "allstate_like", "rows": n_rows,
            "iters": n_iters, "binning_s": round(t_bin, 1),
            "train_s": round(t_train, 1), "train_auc": round(float(a), 6),
            "ref_train_s": ALLSTATE_SECONDS,
            "speedup_vs_ref_cpu": round(
                ALLSTATE_SECONDS / t_train * (n_iters / 500)
                * (n_rows / ALLSTATE_ROWS_REF), 3)}


def run_yahoo(n_rows, n_iters):
    """Yahoo-LTR-shaped lambdarank training (700 dense features)."""
    import lightgbm_tpu as lgb
    X, y, group = make_yahoo_like(n_rows)
    t0 = time.time()
    ds = lgb.Dataset(X, y, group=group)
    ds.construct()
    t_bin = time.time() - t0
    params = {"objective": "lambdarank", "num_leaves": 255, "max_bin": 255,
              "verbosity": -1, "metric": "none",
              "lambdarank_truncation_level": 30}
    warm = lgb.train(dict(params), ds, 17, verbose_eval=False)
    warm._booster._materialize_pending()
    del warm
    t0 = time.time()
    bst = lgb.train(dict(params), ds, n_iters, verbose_eval=False)
    bst._booster._materialize_pending()
    t_train = time.time() - t0
    bst._booster._sync_persist_scores()
    raw = np.asarray(bst._booster.train_score.score_device(0))
    nd = ndcg_at_k(y, raw, group, 10)
    return {"experiment": "yahoo_ltr_like", "rows": len(y),
            "iters": n_iters, "binning_s": round(t_bin, 1),
            "train_s": round(t_train, 1), "train_ndcg10": round(nd, 6),
            "ref_train_s": YAHOO_SECONDS,
            "speedup_vs_ref_cpu": round(
                YAHOO_SECONDS / t_train * (n_iters / 500)
                * (len(y) / YAHOO_ROWS_REF), 3)}


# ---- grower-knob sweep (absorbed from the retired repo-root ----
# ---- sweep_perf.py so the perf gate sees one bench surface)  ----

KNOB_SWEEP_CONFIGS = [
    # decompose: fixed-per-split vs row-cost
    (1_000_000, 15, 255, 2048, "f32"),
    (1_000_000, 15, 63, 2048, "f32"),
    (250_000, 15, 255, 2048, "f32"),
    (1_000_000, 15, 255, 1024, "f32"),
    (1_000_000, 15, 255, 2048, "bf16x2"),
    (1_000_000, 15, 255, 4096, "f32"),
]


def run_knob_sweep_config(n_rows, n_iters, leaves, wc, hd, ds_cache={}):
    """One grower-knob config on the real chip (dev tool, not CI)."""
    import lightgbm_tpu as lgb
    if n_rows not in ds_cache:
        X, y = make_higgs_like(n_rows)
        t0 = time.time()
        ds = lgb.Dataset(X, y)
        ds.construct()
        print(f"# binning {n_rows} rows: {time.time()-t0:.1f}s", flush=True)
        ds_cache[n_rows] = ds
    ds = ds_cache[n_rows]
    params = {"objective": "binary", "num_leaves": leaves, "max_bin": 255,
              "verbosity": -1, "metric": "none",
              "tpu_window_chunk": wc, "tpu_hist_dtype": hd}
    t0 = time.time()
    # 17 = one fused 16-iteration scan + one single-tree program: compiles
    # BOTH steady-state paths so the measured run is compile-free
    warm = lgb.train(dict(params), ds, 17, verbose_eval=False)
    warm._booster._materialize_pending()
    compile_s = time.time() - t0
    del warm
    t0 = time.time()
    bst = lgb.train(dict(params), ds, n_iters, verbose_eval=False)
    bst._booster._materialize_pending()
    import jax
    jax.block_until_ready(bst._booster.train_score.score_device(0))
    train_s = time.time() - t0
    thr = n_rows * n_iters / train_s / 1e6
    print(f"rows={n_rows:8d} iters={n_iters} leaves={leaves:3d} wc={wc:6d} "
          f"hist={hd:6s} compile={compile_s:5.1f}s train={train_s:6.1f}s "
          f"({train_s/n_iters*1000:7.1f} ms/tree) {thr:7.3f} Mri/s",
          flush=True)


def knob_sweep(argv):
    configs = KNOB_SWEEP_CONFIGS
    if argv:
        configs = [tuple(int(x) if x.isdigit() else x for x in a.split(","))
                   for a in argv]
    for cfg in configs:
        run_knob_sweep_config(*cfg)


if __name__ == "__main__":
    # at the END so direct execution sees every run_* defined above
    if len(sys.argv) > 1 and sys.argv[1] == "--knob-sweep":
        knob_sweep(sys.argv[2:])
    else:
        main()
