"""Op-level device-time profile of a short training run (dev tool, not CI).

Traces N boosting iterations on the real chip, then parses the xplane proto
directly (the tensorboard converter is broken against the installed TF) and
prints device time per XLA op name, grouped, sorted by total duration.

Usage: python prof_trace.py [rows] [iters]
"""
import os
import sys
import time

os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")

import numpy as np
import jax


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 16

    import lightgbm_tpu as lgb
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from bench import make_higgs_like

    X, y = make_higgs_like(rows)
    ds = lgb.Dataset(X, y)
    ds.construct()
    params = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
              "verbosity": -1, "metric": "none"}
    # warmup/compile
    warm = lgb.train(dict(params), ds, 17, verbose_eval=False)
    warm._booster._materialize_pending()
    del warm

    tdir = "/tmp/lgbtrace"
    os.system(f"rm -rf {tdir}")
    jax.profiler.start_trace(tdir)
    t0 = time.time()
    booster = lgb.train(dict(params), ds, iters, verbose_eval=False)
    booster._booster._materialize_pending()
    jax.block_until_ready(booster._booster.train_score.score_device(0))
    wall = time.time() - t0
    jax.profiler.stop_trace()
    print(f"wall={wall:.3f}s rows={rows} iters={iters} "
          f"-> {rows*iters/wall/1e6:.2f} Mri/s")

    # ---- parse xplane ----
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    import glob
    path = glob.glob(f"{tdir}/**/*.xplane.pb", recursive=True)[0]
    sp = xplane_pb2.XSpace()
    sp.ParseFromString(open(path, "rb").read())
    for plane in sp.planes:
        if "TPU" not in plane.name and "Axon" not in plane.name:
            continue
        ev_meta = {m.id: m.name for m in plane.event_metadata.values()}
        totals = {}
        counts = {}
        for line in plane.lines:
            if "XLA Ops" not in line.name:
                continue
            for ev in line.events:
                name = ev_meta.get(ev.metadata_id, "?")
                totals[name] = totals.get(name, 0) + ev.duration_ps
                counts[name] = counts.get(name, 0) + 1
        if not totals:
            continue
        print(f"== plane: {plane.name} ==")
        tot_all = sum(totals.values())
        print(f"total device time: {tot_all/1e12:.3f}s "
              f"({tot_all/1e12/iters*1000:.1f} ms/tree)")
        for name, ps in sorted(totals.items(), key=lambda kv: -kv[1])[:40]:
            print(f"{ps/1e12:8.3f}s {ps/1e12/iters*1000:7.2f}ms/tree "
                  f"x{counts[name]:<7d} {name[:90]}")


if __name__ == "__main__":
    main()
