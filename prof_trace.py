"""Op-level device-time profile of a short training run (dev tool, not CI).

Thin wrapper kept for muscle memory: the xplane parsing and the traced
training run now live in the package — see
``lightgbm_tpu/telemetry/xplane.py`` and ``lightgbm_tpu/profile.py``.

Usage: python prof_trace.py [rows] [iters]   (== python -m lightgbm_tpu.profile)
"""
import sys

from lightgbm_tpu.profile import main

if __name__ == "__main__":
    sys.exit(main())
