"""cProfile of Dataset construction (dev tool, not CI). Thin wrapper over
lightgbm_tpu.telemetry.hostprof.profile_binning."""
from lightgbm_tpu.telemetry.hostprof import profile_binning

if __name__ == "__main__":
    profile_binning(500_000)
