import cProfile
import pstats
import sys

from bench import make_higgs_like

import lightgbm_tpu as lgb

X, y = make_higgs_like(500_000)
pr = cProfile.Profile()
pr.enable()
ds = lgb.Dataset(X, y)
ds.construct()
pr.disable()
st = pstats.Stats(pr)
st.sort_stats("cumulative").print_stats(25)
